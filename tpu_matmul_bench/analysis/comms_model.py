"""Analytic comms model: the collectives each parallelism mode MUST emit.

Derived from the mode definitions in `parallel/modes.py`, not from tracing
— that independence is the point: the auditor traces the real programs and
diffs the observed inventory against this model, so a refactor that
accidentally adds, drops, or swaps a collective is caught even when the
numerics still validate (e.g. an all_gather of already-reduced copies is
numerically identical to a psum but moves d× the bytes).

Payload bytes are per-shard operand bytes of the collective — the same
quantity `jaxpr_tools.collective_inventory` measures — for a square
[size, size] problem in `dtype`:

- independent: every device runs its own matmul; no collectives.
- batch_parallel: per-device partial sum over the local batch, then one
  all_reduce of the [local_batch-summed] output — operand [lb, n, n]
  after the local stack (the reference keeps the batch dim, lb = B/d).
- data_parallel: same gradient-sync shape with one replica per device —
  all_reduce of [1, n, n].
- matrix_parallel: column-sharded weights; one all_gather of each
  device's [n, n/d] output columns. Degenerates to independent at d=1
  (modes.py falls back before building the program).
- model_parallel: row×col contraction shards; one all_reduce of the
  full [n, n] partial product.
- hybrid (2-D dp×tp mesh): one all_gather of the [lb, n, n/tp] output
  columns over 'tp', then one all_reduce of the batch-summed [n, n] over
  'dp'.
- summa (2-D r×c grid): per scan step, one masked-psum broadcast of the
  [n/r, n/s] A panel over 'j' and one of the [n/s, n/c] B panel over 'i'
  (statically: the scan body's two all_reduce eqns, counted once).

**Wire-format term (PR 10):** when `--comm-quant` selects a quantized
wire format, every float collective above is rewritten on the wire — an
all_reduce becomes the quantized ring ((d−1) ppermute hops of the
1-byte payload chunk, (d−1) ppermute hops of the fp32 scale side-channel,
then one all_gather of each) and an all_gather carries the 1-byte payload
plus the scale gather. `wire_collectives` predicts that inventory
statically (COLL-Q-002 diffs the traced programs against it) and
`wire_bytes_summary` prices it: payload bytes and scale side-channel
bytes are reported separately, because the headline ≥2× reduction vs
bf16 is a *payload* property — the scale channel adds 4/B bytes per
payload byte for block size B (4/cols for the per-row formats).
"""

from __future__ import annotations

import dataclasses
import math

import numpy as np

# wire-traffic factor per payload byte for a ring algorithm, by kind —
# informational (reported in findings details), not part of the pass/fail
# comparison, which is on exact payload bytes.
RING_WIRE_FACTOR = {
    "all_reduce": lambda d: 2.0 * (d - 1) / d,
    "all_gather": lambda d: float(d - 1),
    "reduce_scatter": lambda d: (d - 1) / d,
    "ppermute": lambda d: 1.0,
    "all_to_all": lambda d: (d - 1) / d,
}


@dataclasses.dataclass(frozen=True)
class ExpectedCollective:
    kind: str
    payload_bytes: int


def _itemsize(dtype) -> int:
    return np.dtype(dtype).itemsize


def matmul_out_itemsize(dtype) -> int:
    """Output itemsize of the suite's matmul for operand dtype: integer
    operands accumulate to int32 (ops/matmul.py preferred_element_type);
    float operands keep their dtype at the program boundary."""
    dt = np.dtype(dtype)
    if np.issubdtype(dt, np.integer):
        return np.dtype(np.int32).itemsize
    return dt.itemsize


def mode_collective_shapes(
        mode: str, world: int, size: int, batch: int = 4,
        dp: int | None = None, rows: int | None = None,
) -> list[tuple[str, int, tuple[int, ...]]]:
    """The float collectives of one mode's FULL program as
    ``(kind, axis_size, per_device_operand_shape)`` triples — the common
    base of the exact inventory model (`expected_collectives`) and the
    wire-format term (`wire_collectives` / `wire_bytes_summary`).

    For the scanned summa mode the scan body is counted ONCE (the static
    inventory semantics of `jaxpr_tools.collective_inventory`); physical
    per-run traffic multiplies by `mode_steps`.
    """
    n = size
    if mode == "independent":
        return []
    if mode == "batch_parallel":
        lb = max(batch // world, 1)
        return [("all_reduce", world, (lb, n, n))]
    if mode == "data_parallel":
        return [("all_reduce", world, (1, n, n))]
    if mode == "matrix_parallel":
        if world == 1:
            return []  # modes.py falls back to independent
        return [("all_gather", world, (n, n // world))]
    if mode == "model_parallel":
        return [("all_reduce", world, (n, n))]
    if mode == "hybrid":
        if not dp or world % dp:
            raise ValueError(f"hybrid mode needs dp dividing world={world}")
        tp = world // dp
        lb = max(batch // dp, 1)
        return [("all_gather", tp, (lb, n, n // tp)),
                ("all_reduce", dp, (n, n))]
    if mode == "summa":
        r = rows or max(d for d in range(1, int(math.isqrt(world)) + 1)
                        if world % d == 0)
        c = world // r
        s = math.lcm(r, c)
        return [("all_reduce", c, (n // r, n // s)),   # A panel over 'j'
                ("all_reduce", r, (n // s, n // c))]   # B panel over 'i'
    raise ValueError(f"no comms model for mode {mode!r}")


def mode_steps(mode: str, world: int, rows: int | None = None) -> int:
    """Collective-emitting steps one program run performs (1 except for
    summa's k-panel scan)."""
    if mode != "summa":
        return 1
    r = rows or max(d for d in range(1, int(math.isqrt(world)) + 1)
                    if world % d == 0)
    return math.lcm(r, world // r)


def expected_collectives(mode: str, world: int, size: int, dtype,
                         batch: int = 4, dp: int | None = None,
                         rows: int | None = None) -> list[ExpectedCollective]:
    """Expected collective inventory for one mode's FULL (compute+comm)
    program with exact (full-precision) collectives. Compute-only
    programs expect [] for every mode."""
    item = matmul_out_itemsize(dtype)
    return [
        ExpectedCollective(kind, int(np.prod(shape)) * item)
        for kind, _, shape in mode_collective_shapes(
            mode, world, size, batch=batch, dp=dp, rows=rows)
    ]


_SCALE_ITEMSIZE = 4  # scales are always fp32
_WIRE_ITEMSIZE = 1   # int8 and float8_e4m3fn payloads are both 1 byte


def _wire_entries(mode: str, world: int, size: int, dtype, comm_quant,
                  batch: int = 4, dp: int | None = None,
                  rows: int | None = None,
                  ) -> list[tuple[str, int, int, str]]:
    """The quantized FULL program's collectives as
    ``(kind, axis_size, payload_bytes, role)`` with role ∈ {payload,
    scale}. Mirrors `wire_psum`/`wire_all_gather` exactly: an all_reduce
    becomes the (d−1)-hop ppermute ring + final all_gather, each hop
    carrying a payload chunk and its scale chunk; an all_gather carries
    the whole shard + scales; size-1 axes and integer operands
    short-circuit to the exact collective.
    """
    from tpu_matmul_bench.parallel.collectives import parse_wire_format

    fmt = parse_wire_format(comm_quant)
    base = mode_collective_shapes(mode, world, size, batch=batch, dp=dp,
                                  rows=rows)
    if fmt is None or np.issubdtype(np.dtype(dtype), np.integer):
        item = matmul_out_itemsize(dtype)
        return [(kind, axis, int(np.prod(shape)) * item, "payload")
                for kind, axis, shape in base]
    out: list[tuple[str, int, int, str]] = []
    for kind, axis, shape in base:
        if axis == 1:
            continue  # the d==1 short-circuit emits no collective at all
        n_rows = int(np.prod(shape[:-1]))
        cols = int(shape[-1])
        nb = fmt.scale_blocks(cols)
        if kind == "all_reduce":
            if n_rows % axis:
                raise ValueError(
                    f"{mode}: flattened rows {n_rows} must divide the "
                    f"{axis}-device axis for the quantized ring")
            chunk = n_rows // axis
            for _ in range(axis - 1):  # reduce-scatter phase, per hop
                out.append(("ppermute", axis,
                            chunk * cols * _WIRE_ITEMSIZE, "payload"))
                out.append(("ppermute", axis,
                            chunk * nb * _SCALE_ITEMSIZE, "scale"))
            out.append(("all_gather", axis,
                        chunk * cols * _WIRE_ITEMSIZE, "payload"))
            out.append(("all_gather", axis,
                        chunk * nb * _SCALE_ITEMSIZE, "scale"))
        elif kind == "all_gather":
            out.append(("all_gather", axis,
                        n_rows * cols * _WIRE_ITEMSIZE, "payload"))
            out.append(("all_gather", axis,
                        n_rows * nb * _SCALE_ITEMSIZE, "scale"))
        else:
            raise ValueError(f"no wire model for collective kind {kind!r}")
    return out


def wire_collectives(mode: str, world: int, size: int, dtype, comm_quant,
                     batch: int = 4, dp: int | None = None,
                     rows: int | None = None) -> list[ExpectedCollective]:
    """Expected collective inventory of the FULL program under
    `--comm-quant` — what COLL-Q-002 diffs the traced quantized programs
    against (the quantized analogue of `expected_collectives`)."""
    return [ExpectedCollective(kind, payload)
            for kind, _, payload, _ in _wire_entries(
                mode, world, size, dtype, comm_quant, batch=batch, dp=dp,
                rows=rows)]


def wire_bytes_summary(mode: str, world: int, size: int, dtype, comm_quant,
                       batch: int = 4, dp: int | None = None,
                       rows: int | None = None) -> dict:
    """Static wire-byte prices for one (mode, world, size, format) cell —
    the bandwidth axis of the accuracy-vs-bandwidth frontier.

    All byte totals are physical ring-wire bytes per program run
    (payload_bytes × RING_WIRE_FACTOR[kind], × the scan steps for summa).
    `payload_reduction_x` is baseline ÷ quantized-payload — the ISSUE's
    ≥2× headline (exactly 2.0 for bf16 → any 1-byte wire format, 4.0 for
    fp32) — while `wire_reduction_x` also charges the fp32 scale
    side-channel (→ 2/(1 + 4/B) for bf16 at block size B).
    """
    from tpu_matmul_bench.parallel.collectives import parse_wire_format

    fmt = parse_wire_format(comm_quant)
    steps = mode_steps(mode, world, rows=rows)
    item = matmul_out_itemsize(dtype)
    baseline = steps * sum(
        int(np.prod(shape)) * item * RING_WIRE_FACTOR[kind](axis)
        for kind, axis, shape in mode_collective_shapes(
            mode, world, size, batch=batch, dp=dp, rows=rows))
    totals = {"payload": 0.0, "scale": 0.0}
    for kind, axis, payload, role in _wire_entries(
            mode, world, size, dtype, comm_quant, batch=batch, dp=dp,
            rows=rows):
        totals[role] += steps * payload * RING_WIRE_FACTOR[kind](axis)
    payload_b, scale_b = totals["payload"], totals["scale"]
    out = {
        "wire_format": comm_quant,
        "block": fmt.block if fmt else None,
        "baseline_bytes": int(round(baseline)),
        "wire_payload_bytes": int(round(payload_b)),
        "wire_scale_bytes": int(round(scale_b)),
        "wire_bytes": int(round(payload_b + scale_b)),
    }
    if payload_b:
        out["payload_reduction_x"] = round(baseline / payload_b, 4)
        out["wire_reduction_x"] = round(baseline / (payload_b + scale_b), 4)
    return out
