"""HLO-level schedule audits: certify the overlap suite's scheduling
preconditions on the optimized HLO, no TPU required (SCHED-*).

The PR-4 auditor stops at the jaxpr — it sees which collectives a program
*contains*, never whether the XLA scheduler is *allowed* to hide them.
This pass compiles every overlap-capable mode at a small size on the CPU
mesh and checks the property the paper's overlap win rests on: a
collective and a matmul can be scheduled concurrently only if neither
reaches the other through def-use edges of the optimized HLO
(`tests/test_hlo_schedule.py` asserts the same structurally; this pass
makes it a lint rule with a stable ID so `lint --fail-on error` and the
campaign pre-gate catch a serializing refactor before device time burns).

Four rules:

- SCHED-001 — forced serialization: the scan body's collective transitively
  consumes the same step's matmul product. REQUIRED on the `no_overlap`
  baseline (that dependency is what makes it a baseline); an ERROR on
  overlap paths (no scheduler may hide a collective that waits on the
  product it follows).
- SCHED-002 — mutual independence: in `overlap`/`pipeline` bodies the
  matmul must not depend on the step's collective either (and must not
  have been hoisted out of the body) — the precondition for XLA's
  latency-hiding scheduler to run them concurrently.
- SCHED-003 — ppermute-ring contract: hop count per ring step, hop
  independence from matmul products on all-gather rings (hops stream raw
  chunks), matmul independence from hops on reduce-scatter rings (the MXU
  never stalls on ICI), and the serialized gather/scatter baselines
  keeping their collective on the matmul's dependency path.
- SCHED-004 — async start/done pairing where the backend emits it (the
  TPU latency-hiding scheduler's `-start`/`-done` split): every start
  needs its done, and the overlap body must schedule a matmul between
  them. XLA:CPU lowers collectives synchronously, so this rule is
  typically silent on the lint mesh — it exists for TPU-side HLO dumps
  fed through the same checkers.

The Pallas ring modes (`pallas_ring*`) are deliberately NOT audited here:
their schedule is hand-written inside one kernel (RDMA double-buffering),
so XLA's scheduler preconditions do not apply, and their CPU lowering is
an interpreter artifact with no scheduling structure to inspect.
"""

from __future__ import annotations

import functools

import jax

from tpu_matmul_bench.analysis import hlo_tools as ht
from tpu_matmul_bench.analysis.findings import Finding

# same small problem as tests/test_hlo_schedule.py: the dependency
# structure is size-invariant, so compile the cheapest size that shards
SCHED_SIZE = 64
# two worlds so hop/matmul counts (which scale with d) are checked at two
# ring lengths, same cross-check discipline as the collective inventory
SCHED_WORLDS = (4, 8)


def _cfg():
    from tpu_matmul_bench.analysis.auditor import _audit_config

    return _audit_config("bfloat16", "xla")


def _mesh(world: int):
    from tpu_matmul_bench.parallel.mesh import make_mesh

    return make_mesh(jax.devices()[:world])


@functools.lru_cache(maxsize=None)
def scan_variant_text(variant: str, world: int,
                      size: int = SCHED_SIZE) -> str:
    """Optimized HLO of one overlap-suite scan variant (compiled once per
    process; the tests and every pass share this cache)."""
    from tpu_matmul_bench.parallel.overlap import overlap_mode

    setup = overlap_mode(_cfg(), _mesh(world), size, variant)
    return ht.compiled_text(setup.full, *setup.operands)


def _ring_operands(world: int, size: int, rs: bool):
    from jax.sharding import PartitionSpec as P

    from tpu_matmul_bench.parallel.mesh import sharded_normal

    cfg = _cfg()
    mesh = _mesh(world)
    x_spec, w_spec = (P(None, "x"), P("x", None)) if rs \
        else (P("x", None), P(None, "x"))
    (x,) = sharded_normal(cfg.seed, (size, size), cfg.dtype, mesh, x_spec,
                          count=1)
    (w,) = sharded_normal(cfg.seed + 1, (size, size), cfg.dtype, mesh,
                          w_spec, count=1)
    return mesh, x, w


@functools.lru_cache(maxsize=None)
def ring_text(kind: str, world: int, size: int = SCHED_SIZE) -> str:
    """Optimized HLO of one collective-matmul ring program. `kind` is one
    of ag / ag_bidir / ag_base / rs / rs_bidir / rs_base."""
    from tpu_matmul_bench.parallel.overlap import (
        collective_matmul_bidir_program,
        collective_matmul_bidir_rs_program,
        collective_matmul_program,
        collective_matmul_rs_program,
    )

    rs = kind.startswith("rs")
    mesh, x, w = _ring_operands(world, size, rs)
    builders = {
        "ag": lambda: collective_matmul_program(mesh, overlap=True),
        "ag_bidir": lambda: collective_matmul_bidir_program(mesh),
        "ag_base": lambda: collective_matmul_program(mesh, overlap=False),
        "rs": lambda: collective_matmul_rs_program(mesh, overlap=True),
        "rs_bidir": lambda: collective_matmul_bidir_rs_program(mesh),
        "rs_base": lambda: collective_matmul_rs_program(mesh, overlap=False),
    }
    return ht.compiled_text(builders[kind](), x, w)


# --------------------------------------------------------------- checkers
# Pure functions over HLO text → findings, so seeded-regression fixtures
# (tests/test_hlo_sched.py) can feed mutated programs straight in.

def check_scan_variant(text: str, variant: str, where: str) -> list[Finding]:
    """SCHED-001/-002/-004 for one {no_overlap, overlap, pipeline} scan
    program's optimized HLO."""
    comps = ht.parse_hlo(text)
    bodies = ht.find_computations_with(comps, "all-reduce")
    if len(bodies) != 1:
        return [Finding(
            "SCHED-002", where,
            f"expected exactly one scan body holding the all-reduce, found "
            f"{len(bodies)} — the step structure the overlap claim rests on "
            "is gone",
            details={"bodies": sorted(b.name for b in bodies)})]
    body = bodies[0]
    findings: list[Finding] = []
    ars = ht.instructions_of(body, "all-reduce")
    serialized = any(ht.reaches_opcode(comps, body, ar, ht.MATMUL_OPS)
                     for ar in ars)
    if variant == "no_overlap":
        if not serialized:
            findings.append(Finding(
                "SCHED-001", where,
                "baseline no longer serialized: the all-reduce does not "
                "consume the step's matmul product, so the scheduler may "
                "overlap them and the no_overlap leg measures nothing",
                details={"variant": variant}))
        return findings
    if serialized:
        findings.append(Finding(
            "SCHED-001", where,
            "overlap path serialized: the collective transitively consumes "
            "the same step's matmul product — no scheduler may hide it",
            details={"variant": variant}))
    dots = ht.instructions_of(body, *ht.MATMUL_OPS)
    if not dots:
        findings.append(Finding(
            "SCHED-002", where,
            "matmul missing from the scan body (hoisted?) — nothing left "
            "to hide the collective behind",
            details={"variant": variant}))
    elif any(ht.reaches_opcode(comps, body, dot, ("all-reduce",))
             for dot in dots):
        findings.append(Finding(
            "SCHED-002", where,
            "the matmul depends on the step's all-reduce — mutual "
            "independence (the latency-hiding precondition) is broken",
            details={"variant": variant}))
    findings.extend(check_async_pairs(text, where,
                                      require_bracketed_matmul=True))
    return findings


def _ring_comp(text: str, where: str):
    comps = ht.parse_hlo(text)
    cands = ht.find_computations_with(comps, "collective-permute")
    if len(cands) != 1:
        return comps, None, [Finding(
            "SCHED-003", where,
            f"expected exactly one computation holding the ppermute ring, "
            f"found {len(cands)}",
            details={"candidates": sorted(c.name for c in cands)})]
    return comps, cands[0], []


def check_ag_ring(text: str, where: str, world: int,
                  bidir: bool = False) -> list[Finding]:
    """SCHED-003 for an all-gather ring: hops stream raw operand chunks
    (never products) and at least one matmul (the resident chunk's) waits
    on no hop at all."""
    comps, comp, findings = _ring_comp(text, where)
    if comp is None:
        return findings
    perms = ht.instructions_of(comp, "collective-permute")
    dots = ht.instructions_of(comp, *ht.MATMUL_OPS)
    exp_perms = (2 if bidir else 1) * (world - 1)
    exp_dots = 2 * world - 1 if bidir else world
    if len(perms) != exp_perms or len(dots) != exp_dots:
        findings.append(Finding(
            "SCHED-003", where,
            f"ring shape mismatch: {len(perms)} hops / {len(dots)} matmuls "
            f"(expected {exp_perms} / {exp_dots} at d={world})",
            details={"hops": len(perms), "matmuls": len(dots),
                     "expected_hops": exp_perms,
                     "expected_matmuls": exp_dots}))
    for p in perms:
        if ht.reaches_opcode(comps, comp, p, ht.MATMUL_OPS):
            findings.append(Finding(
                "SCHED-003", where,
                "an all-gather ring hop depends on a matmul product — the "
                "ring no longer streams raw chunks, so every hop waits on "
                "the MXU",
                details={"hop": p.name}))
    if dots and not any(
            not ht.reaches_opcode(comps, comp, dt, ("collective-permute",))
            for dt in dots):
        findings.append(Finding(
            "SCHED-003", where,
            "every matmul waits on a hop — the resident-chunk overlap "
            "(the t=0 matmul that needs no transfer) is gone",
            details={"matmuls": len(dots)}))
    findings.extend(check_async_pairs(text, where))
    return findings


def check_rs_ring(text: str, where: str, world: int,
                  bidir: bool = False) -> list[Finding]:
    """SCHED-003 for a reduce-scatter ring: the accumulator hops DO carry
    products, but no matmul may ever wait on a hop (each step's product
    comes from the local shard, so the MXU never stalls on ICI)."""
    comps, comp, findings = _ring_comp(text, where)
    if comp is None:
        return findings
    perms = ht.instructions_of(comp, "collective-permute")
    dots = ht.instructions_of(comp, *ht.MATMUL_OPS)
    exp_perms = (2 if bidir else 1) * (world - 1)
    exp_dots = 2 * world if bidir else world
    if len(perms) != exp_perms or len(dots) != exp_dots:
        findings.append(Finding(
            "SCHED-003", where,
            f"ring shape mismatch: {len(perms)} hops / {len(dots)} matmuls "
            f"(expected {exp_perms} / {exp_dots} at d={world})",
            details={"hops": len(perms), "matmuls": len(dots),
                     "expected_hops": exp_perms,
                     "expected_matmuls": exp_dots}))
    for dt in dots:
        if ht.reaches_opcode(comps, comp, dt, ("collective-permute",)):
            findings.append(Finding(
                "SCHED-003", where,
                "a matmul depends on a ring hop — the reduce-scatter "
                "overlap has been serialized (the MXU stalls on ICI)",
                details={"matmul": dt.name}))
    findings.extend(check_async_pairs(text, where))
    return findings


def check_serialized_baseline(text: str, where: str,
                              collective_op: str) -> list[Finding]:
    """SCHED-001 (required direction) for the gather/scatter baselines:
    the collective must sit on the matmul's dependency path (all-gather
    feeding the matmul) or consume its product (reduce-scatter)."""
    comps = ht.parse_hlo(text)
    cands = ht.find_computations_with(comps, collective_op)
    if len(cands) != 1:
        return [Finding(
            "SCHED-001", where,
            f"expected exactly one computation holding the baseline "
            f"{collective_op}, found {len(cands)}",
            details={"collective": collective_op,
                     "candidates": sorted(c.name for c in cands)})]
    comp = cands[0]
    findings: list[Finding] = []
    if collective_op == "all-gather":
        dots = ht.instructions_of(comp, *ht.MATMUL_OPS)
        if not dots or not all(
                ht.reaches_opcode(comps, comp, dt, (collective_op,))
                for dt in dots):
            findings.append(Finding(
                "SCHED-001", where,
                "baseline matmul no longer consumes the gathered operand — "
                "the serialized gather-then-matmul baseline is broken",
                details={"collective": collective_op}))
    else:
        for coll in ht.instructions_of(comp, collective_op):
            if not ht.reaches_opcode(comps, comp, coll, ht.MATMUL_OPS):
                findings.append(Finding(
                    "SCHED-001", where,
                    f"baseline {collective_op} no longer consumes the "
                    "partial product — the serialized baseline is broken",
                    details={"collective": collective_op,
                             "instr": coll.name}))
    return findings


def check_async_pairs(text: str, where: str,
                      require_bracketed_matmul: bool = False
                      ) -> list[Finding]:
    """SCHED-004 where the backend emits async collective pairs: every
    `<op>-start` needs a matching `<op>-done`, and (on overlap bodies)
    a matmul must be scheduled between the first pair."""
    findings: list[Finding] = []
    clean = ht._QUOTED.sub('""', text)
    any_starts = False
    for stem in ht.ASYNC_COLLECTIVE_STEMS:
        starts = clean.count(f"{stem}-start(")
        dones = clean.count(f"{stem}-done(")
        if starts or dones:
            any_starts = any_starts or starts
            if starts != dones:
                findings.append(Finding(
                    "SCHED-004", where,
                    f"{starts} {stem}-start vs {dones} {stem}-done — the "
                    "async pair the latency-hiding scheduler created is "
                    "torn",
                    details={"op": stem, "starts": starts, "dones": dones}))
    if require_bracketed_matmul and any_starts and not findings:
        lines = clean.splitlines()
        start = next(i for i, ln in enumerate(lines)
                     if "all-reduce-start(" in ln or "-start(" in ln)
        done = next((i for i, ln in enumerate(lines[start + 1:], start + 1)
                     if "-done(" in ln), len(lines))
        if not any(any(f" {op}(" in ln for op in ht.MATMUL_OPS)
                   for ln in lines[start + 1:done]):
            findings.append(Finding(
                "SCHED-004", where,
                "no matmul scheduled between the collective's start and "
                "done — the async pair hides nothing",
                details={"start_line": start, "done_line": done}))
    return findings


# ------------------------------------------------------------------ audit

SCAN_VARIANTS = ("no_overlap", "overlap", "pipeline")

_RING_CHECKS = (
    # (kind, checker, kwargs)
    ("ag", check_ag_ring, {}),
    ("ag_bidir", check_ag_ring, {"bidir": True}),
    ("rs", check_rs_ring, {}),
    ("rs_bidir", check_rs_ring, {"bidir": True}),
)

_BASELINE_CHECKS = (
    ("ag_base", "all-gather"),
    ("rs_base", "reduce-scatter"),
)


def audit_hlo_sched(worlds=SCHED_WORLDS,
                    size: int = SCHED_SIZE) -> list[Finding]:
    """Compile and audit every overlap-capable mode at every world size:
    scan variants, AG/RS rings (uni + bidir), and the serialized
    baselines. Pure structure — nothing is executed beyond the one-time
    ring prologue fill."""
    findings: list[Finding] = []
    avail = len(jax.devices())
    for world in worlds:
        if world > avail:
            findings.append(Finding(
                "SCHED-002", f"mesh:d{world}",
                f"cannot audit world={world}: only {avail} devices (run "
                "under XLA_FLAGS=--xla_force_host_platform_device_count)",
                severity="warn", details={"available": avail}))
            continue
        for variant in SCAN_VARIANTS:
            findings.extend(check_scan_variant(
                scan_variant_text(variant, world, size), variant,
                f"sched:{variant}@d{world}"))
        for kind, checker, kw in _RING_CHECKS:
            findings.extend(checker(
                ring_text(kind, world, size),
                f"sched:{kind}@d{world}", world, **kw))
        for kind, coll in _BASELINE_CHECKS:
            findings.extend(check_serialized_baseline(
                ring_text(kind, world, size),
                f"sched:{kind}@d{world}", coll))
    return findings
