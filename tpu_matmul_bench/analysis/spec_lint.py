"""Offline campaign/serve spec validation — lint before you burn TPU hours.

`campaign/spec.py` validates what it must to build a job plan (top-level
keys, programs, duplicate ids); everything else is deliberately permissive
at run time. That permissiveness is where typos hide: an unknown job-level
key (`timout_s`) is silently ignored, a size that doesn't divide the mesh
fails an hour into the sweep, and two jobs that expand to the same argv
silently share one resume slot. This module checks all of it statically,
without touching a backend.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from tpu_matmul_bench.analysis.findings import Finding

# key vocabulary per spec table, mirroring what campaign/spec.py actually
# reads — anything else is dead weight the executor will never see
_CAMPAIGN_KEYS = {"name"}
_DEFAULTS_KEYS = {"flags", "timeout_s", "retries", "backoff_s",
                  "heartbeat_s"}
_JOB_KEYS = {"id", "program", "flags", "timeout_s", "retries", "backoff_s",
             "heartbeat_s"}
_SWEEP_KEYS = {"id_prefix", "program", "flags", "timeout_s", "retries",
               "backoff_s", "heartbeat_s", "sizes", "modes", "dtypes",
               "num_devices"}

# modes whose program shards the [size, size] problem over the device
# axis and therefore needs size % num_devices == 0
_DIVISIBILITY_MODES = {"matrix_parallel", "model_parallel"}

# serve-CLI subcommands a campaign may schedule (the semantic subset:
# explain/trace/pod are interactive or CI-only) and flag SEMANTICS that
# argparse cannot express (positivity, scheduler vocabulary). The flag
# VOCABULARY itself is derived from the real parsers below — PR 19's
# hand-kept lists had already drifted (--obs-exemplars existed in
# serve/cli.py but not here, so every spec using it was a false
# SPEC-002).
_SERVE_SUBCOMMANDS = ("bench", "ab", "selftest")
# flags whose value must be a strictly positive number
_SERVE_POSITIVE_FLAGS = {"--qps", "--duration", "--concurrency",
                         "--window-ms", "--starvation-ms", "--max-depth",
                         "--max-batch", "--cache-capacity"}
_SERVE_SCHEDULERS = ("fixed", "continuous")

#: derived-parser vocabulary cache; built once per process on first use
_VOCAB_CACHE: dict[str, Any] = {}


def _subparsers_of(parser: Any) -> dict[str, Any]:
    """name -> subparser from an argparse parser's _SubParsersAction."""
    import argparse

    for action in parser._actions:
        if isinstance(action, argparse._SubParsersAction):
            return dict(action.choices)
    return {}


def _flags_of(parser: Any) -> set[str]:
    """The --long option strings a subparser accepts, minus --help."""
    return {opt for action in parser._actions
            for opt in action.option_strings
            if opt.startswith("--")} - {"--help"}


def _bool_flags_of(parser: Any) -> set[str]:
    """The zero-argument (store_true/store_const) --long options."""
    return {opt for action in parser._actions if action.nargs == 0
            for opt in action.option_strings
            if opt.startswith("--")} - {"--help"}


def _serve_vocab() -> tuple[set[str], set[str], set[str]]:
    """(common, bench/ab-only, zero-arg) serve flags, introspected from
    serve/cli.py's real parser — the vocabulary can no longer drift
    from the CLI because it IS the CLI."""
    if "serve" not in _VOCAB_CACHE:
        from tpu_matmul_bench.serve.cli import build_parser

        subs = _subparsers_of(build_parser())
        per = {name: _flags_of(subs[name]) for name in _SERVE_SUBCOMMANDS}
        common = set.intersection(*per.values())
        bench_only = (per["bench"] | per["ab"]) - common
        bools = set().union(*(_bool_flags_of(subs[name])
                              for name in _SERVE_SUBCOMMANDS))
        _VOCAB_CACHE["serve"] = (common, bench_only, bools)
    return _VOCAB_CACHE["serve"]


def _raw_flag_values(argv: list[str], flag: str) -> list[str]:
    """Raw tokens following `flag` up to the next option, NO comma split —
    for values whose grammar owns its commas (per-link --comm-quant,
    --mesh factorizations)."""
    out: list[str] = []
    try:
        i = argv.index(flag)
    except ValueError:
        return out
    for tok in argv[i + 1:]:
        if tok.startswith("--"):
            break
        out.append(tok)
    return out


def _flag_values(argv: list[str], flag: str) -> list[str]:
    """Values following `flag` up to the next option, commas split."""
    return [t for tok in _raw_flag_values(argv, flag)
            for t in tok.split(",") if t]


def _comm_quant_values(argv: list[str]) -> list[str]:
    """--comm-quant values with the per-link grammar respected: a token
    containing '=' is one per-link spec (its commas separate link
    classes, not sweep points); plain tokens keep the sweep-list comma
    split."""
    out: list[str] = []
    for tok in _raw_flag_values(argv, "--comm-quant"):
        if "=" in tok:
            out.append(tok)
        else:
            out.extend(t for t in tok.split(",") if t)
    return out


def _serve_flag_items(argv: list[str], bool_flags: set[str],
                      ) -> tuple[list[tuple[str, str | None]],
                                 list[str]]:
    """(flag, value) pairs + stray positional tokens from a CLI job's
    argv tail (after the subcommand). Handles --flag=value and the
    caller's zero-argument flags; an unknown flag is assumed to take a
    value."""
    items: list[tuple[str, str | None]] = []
    strays: list[str] = []
    i = 0
    while i < len(argv):
        tok = argv[i]
        if not tok.startswith("--"):
            strays.append(tok)
            i += 1
            continue
        flag, eq, inline = tok.partition("=")
        if eq:
            items.append((flag, inline))
            i += 1
        elif flag in bool_flags:
            items.append((flag, None))
            i += 1
        else:
            val = argv[i + 1] if i + 1 < len(argv) \
                and not argv[i + 1].startswith("--") else None
            items.append((flag, val))
            i += 2 if val is not None else 1
    return items, strays


def _lint_serve_job(job: Any, where: str,
                    spec_dir: Path | None = None) -> list[Finding]:
    """The serve analog of the round.toml job checks: subcommand + flag
    vocabulary (SPEC-002), mix/grid/load/scheduler validity (SPEC-001),
    tenant definitions (SPEC-005/SPEC-006), and a padding-grid coverage
    warning (SPEC-003)."""
    from tpu_matmul_bench.serve.loadgen import parse_mix
    from tpu_matmul_bench.serve.queue import DEFAULT_GRID

    argv = list(job.argv)
    if not argv or argv[0] not in _SERVE_SUBCOMMANDS:
        return [Finding(
            "SPEC-001", where,
            f"serve job must start with a subcommand "
            f"{_SERVE_SUBCOMMANDS}, got {argv[:1] or '[]'}",
            details={"argv": argv})]
    sub = argv[0]
    common, bench_only, bool_flags = _serve_vocab()
    known = common | (bench_only if sub in ("bench", "ab") else set())
    findings: list[Finding] = []
    items, strays = _serve_flag_items(argv[1:], bool_flags)
    for tok in strays:
        findings.append(Finding(
            "SPEC-001", where,
            f"stray positional token {tok!r} in serve {sub} flags",
            details={"token": tok}))
    values: dict[str, str | None] = {}
    for flag, val in items:
        if flag not in known:
            findings.append(Finding(
                "SPEC-002", where,
                f"unknown serve {sub} flag {flag!r} (the job would crash "
                "at spawn time)",
                details={"flag": flag, "known": sorted(known)}))
            continue
        values[flag] = val

    mix = values.get("--mix")
    mix_entries = ()
    if mix is not None:
        try:
            mix_entries = parse_mix(mix)
        except ValueError as e:
            findings.append(Finding(
                "SPEC-001", where, f"bad --mix: {e}",
                details={"mix": mix}))
    grid = tuple(DEFAULT_GRID)
    if values.get("--grid") is not None:
        try:
            grid = tuple(int(g) for g in values["--grid"].split(",") if g)
            if not grid or any(g < 1 for g in grid):
                raise ValueError(f"grid needs positive points, got {grid!r}")
        except ValueError as e:
            findings.append(Finding(
                "SPEC-001", where, f"bad --grid: {e}",
                details={"grid": values["--grid"]}))
            grid = tuple(DEFAULT_GRID)
    for flag in sorted(_SERVE_POSITIVE_FLAGS & set(values)):
        try:
            num = float(values[flag])
        except (TypeError, ValueError):
            num = -1.0
        if num <= 0:
            findings.append(Finding(
                "SPEC-001", where,
                f"{flag} must be a positive number, got {values[flag]!r}",
                details={"flag": flag, "value": values[flag]}))
    eps = values.get("--explore")
    if "--explore" in values:
        try:
            eps_num = float(eps) if eps is not None else -1.0
        except ValueError:
            eps_num = -1.0
        if not 0.0 < eps_num <= 1.0:
            findings.append(Finding(
                "SPEC-001", where,
                f"--explore must be a shadow-traffic fraction in (0, 1], "
                f"got {eps!r}",
                details={"explore": eps}))
    sched = values.get("--scheduler")
    if sched is not None and sched not in _SERVE_SCHEDULERS:
        findings.append(Finding(
            "SPEC-001", where,
            f"--scheduler must be one of {_SERVE_SCHEDULERS}, "
            f"got {sched!r}",
            details={"scheduler": sched}))
    if "--tenants" in values:
        findings.extend(
            _lint_tenants_value(values["--tenants"], where, spec_dir))
    # coverage analog of the mesh-divisibility warn: a mix dim above the
    # grid top compiles an off-grid executable per shape (cache churn and
    # padding waste the grid was supposed to bound)
    top = max(grid)
    for entry in mix_entries:
        dims = (entry.m, entry.k, entry.n)
        over = [d for d in dims if d > top]
        if over:
            findings.append(Finding(
                "SPEC-003", where,
                f"mix shape {'x'.join(str(d) for d in dims)} exceeds the "
                f"padding-grid top {top} — each such shape compiles its "
                "own off-grid executable",
                details={"dims": list(dims), "grid_top": top}))
    return findings


# obs-CLI subcommands a campaign may schedule (ingest after a sweep,
# detect as a gate) plus the value semantics argparse cannot express;
# the per-subcommand flag vocabulary is introspected from obs/cli.py's
# real parser, same contract as _serve_vocab
_OBS_SUBCOMMANDS = ("status", "selftest", "ingest", "history", "detect",
                    "report")


def _obs_vocab() -> tuple[dict[str, set[str]], set[str]]:
    """(subcommand -> flags, zero-arg flags) for the observatory CLI,
    introspected from obs/cli.py's real parser."""
    if "obs" not in _VOCAB_CACHE:
        from tpu_matmul_bench.obs.cli import build_parser

        subs = _subparsers_of(build_parser())
        by_sub = {name: _flags_of(subs[name])
                  for name in _OBS_SUBCOMMANDS}
        bools = set().union(*(_bool_flags_of(subs[name])
                              for name in _OBS_SUBCOMMANDS))
        _VOCAB_CACHE["obs"] = (by_sub, bools)
    return _VOCAB_CACHE["obs"]
#: flags that must parse as a strictly positive integer
_OBS_POSITIVE_INT_FLAGS = {"--detect-window", "--stale-rounds", "--seq"}
#: flags that must parse as a strictly positive number
_OBS_POSITIVE_FLAGS = {"--threshold-pct", "--interval", "--timeout"}
#: subcommands whose positional operands are legitimate
_OBS_POSITIONAL_OK = {"status", "ingest"}
_OBS_HISTORY_ACTIONS = ("show", "selftest")


def _lint_obs_job(job: Any, where: str) -> list[Finding]:
    """The observatory analog of `_lint_serve_job`: subcommand check
    (SPEC-001), per-subcommand flag vocabulary (SPEC-002), and value
    validity for the detection windows (SPEC-001) — so a campaign that
    schedules `obs detect --detect-window 0` dies at lint, not an hour
    into the sweep."""
    from tpu_matmul_bench.analysis.findings import SEVERITIES

    argv = list(job.argv)
    if not argv or argv[0] not in _OBS_SUBCOMMANDS:
        return [Finding(
            "SPEC-001", where,
            f"obs job must start with a subcommand {_OBS_SUBCOMMANDS}, "
            f"got {argv[:1] or '[]'}",
            details={"argv": argv})]
    sub = argv[0]
    by_sub, bool_flags = _obs_vocab()
    known = by_sub[sub]
    findings: list[Finding] = []
    # the shared tokenizer, parameterized with obs's own zero-argument
    # flags so `--json`-style options never capture the next token
    fixed_items, strays = _serve_flag_items(argv[1:], bool_flags)
    if sub == "history":
        # optional positional action
        actions = [s for s in strays]
        strays = []
        for act in actions:
            if act not in _OBS_HISTORY_ACTIONS:
                findings.append(Finding(
                    "SPEC-001", where,
                    f"obs history action must be one of "
                    f"{_OBS_HISTORY_ACTIONS}, got {act!r}",
                    details={"action": act}))
    elif sub not in _OBS_POSITIONAL_OK:
        for tok in strays:
            findings.append(Finding(
                "SPEC-001", where,
                f"stray positional token {tok!r} in obs {sub} flags",
                details={"token": tok}))
        strays = []
    values: dict[str, str | None] = {}
    for flag, val in fixed_items:
        if flag not in known:
            findings.append(Finding(
                "SPEC-002", where,
                f"unknown obs {sub} flag {flag!r} (the job would crash "
                "at spawn time)",
                details={"flag": flag, "known": sorted(known)}))
            continue
        values[flag] = val
    for flag in sorted(_OBS_POSITIVE_INT_FLAGS & set(values)):
        val = values[flag]
        try:
            ok = val is not None and int(val) > 0
        except ValueError:
            ok = False
        if not ok:
            findings.append(Finding(
                "SPEC-001", where,
                f"{flag} must be a positive integer, got {val!r}",
                details={"flag": flag, "value": val}))
    for flag in sorted(_OBS_POSITIVE_FLAGS & set(values)):
        val = values[flag]
        try:
            ok = val is not None and float(val) > 0
        except ValueError:
            ok = False
        if not ok:
            findings.append(Finding(
                "SPEC-001", where,
                f"{flag} must be a positive number, got {val!r}",
                details={"flag": flag, "value": val}))
    if "--fail-on" in values and values["--fail-on"] not in SEVERITIES:
        findings.append(Finding(
            "SPEC-001", where,
            f"--fail-on must be one of {SEVERITIES}, "
            f"got {values['--fail-on']!r}",
            details={"fail_on": values["--fail-on"]}))
    return findings


def _lint_tenants_data(data: Any, where: str) -> list[Finding]:
    """All findings for a parsed ``{"tenants": {...}}`` root: unknown
    keys per block (SPEC-002), bounds/profile validity (SPEC-005),
    normalized-id duplicates (SPEC-006). Reports every violation, unlike
    the runtime loader which raises on the first."""
    from tpu_matmul_bench.serve.tenants import (
        TENANT_KEYS,
        TenantSpecError,
        _norm_id,
        tenant_from_dict,
    )

    table = data.get("tenants") if isinstance(data, dict) else None
    if not isinstance(table, dict) or not table:
        return [Finding(
            "SPEC-001", where,
            "tenant file needs a non-empty [tenants.<id>] table")]
    findings: list[Finding] = []
    seen: dict[str, str] = {}
    for tid, entry in table.items():
        label = f"{where}:tenants.{tid}"
        if isinstance(entry, dict):
            for key in sorted(set(entry) - TENANT_KEYS):
                findings.append(Finding(
                    "SPEC-002", label,
                    f"unknown tenant key {key!r} (silently ignored at "
                    "run time)",
                    details={"key": key, "known": sorted(TENANT_KEYS)}))
        try:
            spec = tenant_from_dict(str(tid), entry)
        except TenantSpecError as e:
            findings.append(Finding("SPEC-005", label, str(e),
                                    details={"tenant": str(tid)}))
            continue
        norm = _norm_id(spec.tenant_id)
        if norm in seen:
            findings.append(Finding(
                "SPEC-006", label,
                f"duplicate tenant id {spec.tenant_id!r} (collides with "
                f"{seen[norm]!r} after case/whitespace normalization)",
                details={"tenant": spec.tenant_id,
                         "collides_with": seen[norm]}))
        else:
            seen[norm] = spec.tenant_id
    return findings


def _lint_tenants_value(value: str | None, where: str,
                        spec_dir: Path | None) -> list[Finding]:
    """A serve job's ``--tenants`` value: a TOML path (resolved against
    the cwd like the executor will, then against the spec's directory)
    linted in place, or the inline form parsed the way the CLI would."""
    from tpu_matmul_bench.campaign.spec import CampaignSpecError, _parse_toml
    from tpu_matmul_bench.serve.tenants import (
        TenantSpecError,
        parse_tenants_arg,
    )

    if value is None:
        return [Finding("SPEC-001", where, "--tenants needs a value")]
    if value.endswith(".toml"):
        p = Path(value)
        if not p.exists() and spec_dir is not None:
            p = spec_dir / value
        if not p.exists():
            return [Finding(
                "SPEC-001", where,
                f"--tenants file {value!r} not found (looked in the cwd "
                + (f"and {spec_dir}" if spec_dir else "only") + ")",
                details={"tenants": value})]
        try:
            data = _parse_toml(p.read_text())
        except (OSError, CampaignSpecError) as e:
            return [Finding("SPEC-001", where,
                            f"unreadable --tenants file {p}: {e}",
                            details={"tenants": str(p)})]
        return _lint_tenants_data(data, f"{where}:{value}")
    try:
        parse_tenants_arg(value)
    except TenantSpecError as e:
        rule = "SPEC-006" if "duplicate tenant id" in str(e) else "SPEC-005"
        return [Finding(rule, where, f"bad inline --tenants: {e}",
                        details={"tenants": value})]
    return []


# modes whose collectives --comm-quant rewrites; other modes (independent,
# the overlap family) carry no quantizable float collective, so a block
# size cannot be statically wrong there
_QUANTIZABLE_MODES = {"batch_parallel", "data_parallel", "matrix_parallel",
                      "model_parallel", "hybrid", "summa"}


def _comm_quant_findings(job: Any, label: str) -> list[Finding]:
    """SPEC-007 for one job: parse every --comm-quant value against the
    wire-format grammar, then dry-run the wire model over the job's
    (mode, size, num_devices) grid so block/ring divisibility errors
    surface at lint time instead of mid-campaign."""
    import numpy as np

    from tpu_matmul_bench.analysis.comms_model import wire_collectives
    from tpu_matmul_bench.parallel.collectives import parse_wire_format

    argv = list(job.argv)
    # per-link specs ('=' in the value) are SPEC-008's to validate — the
    # uniform wire grammar below would false-positive on their commas
    quants = [q for q in _comm_quant_values(argv) if "=" not in q]
    if not quants:
        return []
    findings: list[Finding] = []
    dtypes = _flag_values(argv, "--dtype") or ["bfloat16"]
    modes = _QUANTIZABLE_MODES & set(_flag_values(argv, "--mode"))
    devs = [int(x) for x in _flag_values(argv, "--num-devices")
            if x.isdigit()]
    sizes = [int(x) for x in _flag_values(argv, "--sizes") if x.isdigit()]
    dps = [int(x) for x in _flag_values(argv, "--dp") if x.isdigit()]
    for q in quants:
        try:
            fmt = parse_wire_format(q)
        except ValueError as e:
            findings.append(Finding(
                "SPEC-007", label, f"bad --comm-quant value: {e}",
                details={"comm_quant": q}))
            continue
        if fmt is None:
            continue
        if all(dt.startswith(("int", "uint")) for dt in dtypes):
            continue  # integer operands keep the exact collective
        for mode in sorted(modes):
            for d in devs or [1]:
                if d <= 1:
                    continue  # the d==1 short-circuit is always valid
                kw = {"dp": dps[0]} if mode == "hybrid" and dps else (
                    {"dp": 2 if d % 2 == 0 else 1} if mode == "hybrid"
                    else {})
                for s in sizes:
                    try:
                        wire_collectives(mode, d, s, np.float32, q, **kw)
                    except ValueError as e:
                        findings.append(Finding(
                            "SPEC-007", label,
                            f"--comm-quant {q} cannot run "
                            f"--mode {mode} --sizes {s} "
                            f"--num-devices {d}: {e}",
                            details={"comm_quant": q, "mode": mode,
                                     "size": s, "num_devices": d}))
    return findings


#: modes that accept a two-axis --mesh factorization
_HIER_MODES = {"hybrid", "summa"}


def _hier_findings(job: Any, label: str) -> list[Finding]:
    """SPEC-008 for one job: the hierarchical-mesh flag family. --mesh
    values must parse the dcn:R,ici:C grammar and factorize the job's
    --num-devices; per-link --comm-quant values must parse the link
    grammar and dry-run the two-level wire model over the job's
    (program, size) grid; --stream-k must be a positive panel count that
    divides every size; --mem-budget-gib must be a positive number."""
    import math

    import numpy as np

    from tpu_matmul_bench.analysis.comms_model import (
        hier_expected_collectives,
    )
    from tpu_matmul_bench.parallel.collectives import parse_link_formats
    from tpu_matmul_bench.parallel.mesh import parse_mesh_spec

    argv = list(job.argv)
    findings: list[Finding] = []
    devs = [int(x) for x in _flag_values(argv, "--num-devices")
            if x.isdigit()]
    sizes = [int(x) for x in _flag_values(argv, "--sizes") if x.isdigit()]
    hier_progs = _HIER_MODES & (
        {job.program} | set(_flag_values(argv, "--mode")))

    meshes = []
    for m in _raw_flag_values(argv, "--mesh"):
        try:
            axes = parse_mesh_spec(m)
        except ValueError as e:
            findings.append(Finding(
                "SPEC-008", label, f"bad --mesh value: {e}",
                details={"mesh": m}))
            continue
        meshes.append(m)
        total = math.prod(d for _, d in axes)
        for d in devs:
            if d != total:
                findings.append(Finding(
                    "SPEC-008", label,
                    f"--mesh {m} factorizes {total} devices but the job "
                    f"runs --num-devices {d}",
                    details={"mesh": m, "num_devices": d}))

    per_link = [q for q in _comm_quant_values(argv) if "=" in q]
    for q in per_link:
        try:
            parse_link_formats(q)
        except ValueError as e:
            findings.append(Finding(
                "SPEC-008", label, f"bad per-link --comm-quant value: {e}",
                details={"comm_quant": q}))
            continue
        if not meshes:
            findings.append(Finding(
                "SPEC-008", label,
                f"per-link --comm-quant {q} without a --mesh "
                "factorization — there is only one (flat) link class to "
                "route over",
                details={"comm_quant": q}))
        # dry-run the two-level wire model: block/ring divisibility
        # errors surface here instead of mid-campaign
        for m in meshes:
            for prog in sorted(hier_progs):
                for s in sizes:
                    try:
                        hier_expected_collectives(prog, m, s, np.float32, q)
                    except ValueError as e:
                        findings.append(Finding(
                            "SPEC-008", label,
                            f"--comm-quant {q} cannot run {prog} "
                            f"--mesh {m} --sizes {s}: {e}",
                            details={"comm_quant": q, "mesh": m,
                                     "program": prog, "size": s}))

    for tok in _flag_values(argv, "--stream-k"):
        try:
            panels = int(tok)
        except ValueError:
            panels = 0
        if panels <= 0:
            findings.append(Finding(
                "SPEC-008", label,
                f"--stream-k must be a positive panel count, got {tok!r}",
                details={"stream_k": tok}))
            continue
        for s in sizes:
            if s % panels:
                findings.append(Finding(
                    "SPEC-008", label,
                    f"--stream-k {panels} panels do not divide size {s}",
                    details={"stream_k": panels, "size": s}))

    for tok in _flag_values(argv, "--mem-budget-gib"):
        try:
            ok = float(tok) > 0
        except ValueError:
            ok = False
        if not ok:
            findings.append(Finding(
                "SPEC-008", label,
                f"--mem-budget-gib must be a positive number, got {tok!r}",
                details={"mem_budget_gib": tok}))
    return findings


def _pod_findings(job: Any, label: str) -> list[Finding]:
    """SPEC-010 for one serve job: the pod serving flag family.

    --replica-groups must be a positive count that divides the outer
    axis of every --mesh factorization (serve/placement.py's partition
    rule — a group spanning a fractional DCN row is cross-group traffic
    by construction); pod flags without --mesh have no pod to shape;
    --num-devices must cover the mesh world; --scheduler fixed cannot
    place (the pod arm requires the continuous scheduler); and every
    per-link --comm-quant must dry-run the pod collective model over
    the job's mix buckets so wire-format divisibility errors surface at
    lint time, not mid-campaign."""
    import numpy as np

    from tpu_matmul_bench.serve.placement import mesh_world, partition_spec

    argv = list(job.argv)
    findings: list[Finding] = []
    group_toks = _flag_values(argv, "--replica-groups")
    meshes = _raw_flag_values(argv, "--mesh")
    if not meshes:
        if group_toks:
            findings.append(Finding(
                "SPEC-010", label,
                "--replica-groups without --mesh — there is no pod "
                "to partition",
                details={"replica_groups": group_toks}))
        return findings

    if "fixed" in _flag_values(argv, "--scheduler"):
        findings.append(Finding(
            "SPEC-010", label,
            "--mesh with --scheduler fixed: pod placement requires the "
            "continuous scheduler (per-group breakers and SLO state)",
            details={}))

    group_counts: list[int] = []
    for tok in group_toks:
        if not tok.isdigit() or int(tok) < 1:
            findings.append(Finding(
                "SPEC-010", label,
                f"--replica-groups must be a positive count, got {tok!r}",
                details={"replica_groups": tok}))
        else:
            group_counts.append(int(tok))

    devs = [int(x) for x in _flag_values(argv, "--num-devices")
            if x.isdigit()]
    per_link = [q for q in _comm_quant_values(argv) if "=" in q]
    dtypes = _flag_values(argv, "--dtype") or ["float32"]
    buckets = _serve_mix_buckets(argv)
    for m in meshes:
        try:
            world = mesh_world(m)
        except ValueError:
            continue  # grammar errors are SPEC-008's to report
        for d in devs:
            if d < world:
                findings.append(Finding(
                    "SPEC-010", label,
                    f"--mesh {m} spans {world} devices but the job caps "
                    f"--num-devices {d}",
                    details={"mesh": m, "num_devices": d}))
        for g in group_counts or [1]:
            try:
                parts = partition_spec(m, g)
            except ValueError as e:
                findings.append(Finding(
                    "SPEC-010", label, str(e),
                    details={"mesh": m, "replica_groups": g}))
                continue
            if all(dt.startswith(("int", "uint")) for dt in dtypes):
                continue  # integer requests keep the exact collective
            # dry-run the pod collective model per group shape: a block
            # format that cannot tile a bucket's gather payload dies
            # here, not an hour into the campaign
            for q in per_link:
                for bm, bk, bn in buckets:
                    try:
                        from tpu_matmul_bench.analysis.comms_model import (
                            pod_expected_collectives,
                        )

                        pod_expected_collectives(
                            parts[0].mesh_spec, bm, bk, bn,
                            np.float32, q)
                    except ValueError as e:
                        findings.append(Finding(
                            "SPEC-010", label,
                            f"--comm-quant {q} cannot serve bucket "
                            f"{bm}x{bk}x{bn} on a {parts[0].mesh_spec} "
                            f"group of --mesh {m}: {e}",
                            details={"comm_quant": q, "mesh": m,
                                     "replica_groups": g,
                                     "bucket": [bm, bk, bn]}))
    return findings


def _serve_mix_buckets(argv: list[str]) -> list[tuple[int, int, int]]:
    """The padded buckets a serve job's --mix lands on (its --grid or
    the default), deduplicated — what the pod wire model must price."""
    from tpu_matmul_bench.serve.loadgen import DEFAULT_MIX, parse_mix
    from tpu_matmul_bench.serve.queue import ShapeGrid

    mixes = _raw_flag_values(argv, "--mix") or [DEFAULT_MIX]
    grid_toks = [int(t) for t in _flag_values(argv, "--grid")
                 if t.isdigit()]
    try:
        grid = ShapeGrid(grid_toks) if grid_toks else ShapeGrid()
        entries = [e for mx in mixes for e in parse_mix(mx)]
    except ValueError:
        return []  # the mix/grid error is SPEC-001's to report
    return sorted({grid.bucket(e.m, e.k, e.n) for e in entries})


def _lint_train_job(job: Any, label: str) -> list[Finding]:
    """SPEC-009 for one train job: subcommand, the --grad-quant grammar
    (minus the legacy control tier, which has no reduce_scatter half),
    per-link values only with a factorized --mesh, --zero ∈ {0, 1},
    --steps ≥ 2 whenever a quantized wire makes the drift series
    measurable, and a dry run of the gradient-collective model over the
    job's (mode, mesh, size, zero) grid — shape/divisibility rejections
    surface at lint time, not mid-campaign."""
    import numpy as np

    from tpu_matmul_bench.analysis.comms_model import (
        train_expected_collectives,
    )
    from tpu_matmul_bench.parallel.collectives import (
        is_per_link_spec,
        parse_wire_format,
        validate_comm_quant,
    )

    argv = list(job.argv)
    findings: list[Finding] = []
    if not ({"bench", "selftest"} & set(argv)):
        findings.append(Finding(
            "SPEC-009", label,
            "train job names no subcommand: expected 'bench' or "
            "'selftest' in the flags",
            details={"argv": argv}))
        return findings
    if "selftest" in argv:
        return findings  # selftest takes only --quiet; nothing to grid

    if _flag_values(argv, "--comm-quant") or "--comm-quant" in argv:
        findings.append(Finding(
            "SPEC-009", label,
            "train takes --grad-quant (gradient collectives), not "
            "--comm-quant", details={}))

    meshes = _raw_flag_values(argv, "--mesh")
    quants = _raw_flag_values(argv, "--grad-quant")
    for q in quants:
        try:
            validate_comm_quant(q)
            if not is_per_link_spec(q):
                fmt = parse_wire_format(q)
                if fmt is not None and fmt.legacy:
                    raise ValueError(
                        f"{q!r} is the legacy control tier, which has no "
                        "reduce_scatter half")
        except ValueError as e:
            findings.append(Finding(
                "SPEC-009", label, f"bad --grad-quant value: {e}",
                details={"grad_quant": q}))
            continue
        if is_per_link_spec(q) and not meshes:
            findings.append(Finding(
                "SPEC-009", label,
                f"per-link --grad-quant {q} without a --mesh "
                "factorization — there is only one (flat) link class to "
                "route over",
                details={"grad_quant": q}))

    zeros: list[int] = []
    for tok in _flag_values(argv, "--zero"):
        if tok not in ("0", "1"):
            findings.append(Finding(
                "SPEC-009", label,
                f"--zero must be 0 or 1, got {tok!r}",
                details={"zero": tok}))
        else:
            zeros.append(int(tok))

    # a quantized gradient wire makes the drift series measurable; a
    # one-step series is a point, not a drift
    wired = [q for q in quants if q != "none"]
    for tok in _flag_values(argv, "--steps"):
        try:
            steps = int(tok)
        except ValueError:
            steps = 0
        if steps < 1:
            findings.append(Finding(
                "SPEC-009", label,
                f"--steps must be a positive count, got {tok!r}",
                details={"steps": tok}))
        elif steps < 2 and wired:
            findings.append(Finding(
                "SPEC-009", label,
                f"--steps {steps} with a quantized --grad-quant: the "
                "update-error drift series needs at least 2 steps to "
                "show drift",
                details={"steps": steps, "grad_quant": wired}))

    # dry-run the gradient-collective model over the job's grid
    devs = [int(x) for x in _flag_values(argv, "--num-devices")
            if x.isdigit()]
    sizes = [int(x) for x in _flag_values(argv, "--sizes") if x.isdigit()]
    modes = _flag_values(argv, "--mode") or ["dp"]
    for mode in modes:
        for mesh in (meshes or [None]):
            for world in (devs or [1]):
                for s in sizes:
                    for q in (quants or [None]):
                        for z in (zeros or [0]):
                            try:
                                train_expected_collectives(
                                    mode, mesh, world, s, np.float32,
                                    None if q == "none" else q,
                                    zero=bool(z))
                            except ValueError as e:
                                findings.append(Finding(
                                    "SPEC-009", label,
                                    f"train --mode {mode} "
                                    f"--mesh {mesh or '(flat)'} --sizes "
                                    f"{s} --zero {z} cannot run: {e}",
                                    details={"mode": mode, "mesh": mesh,
                                             "size": s, "zero": z,
                                             "grad_quant": q}))
    return findings


def _unknown_key_findings(data: dict[str, Any], where: str) -> list[Finding]:
    findings = []

    def check(table: Any, known: set, label: str) -> None:
        if not isinstance(table, dict):
            return
        for key in sorted(set(table) - known):
            findings.append(Finding(
                "SPEC-002", f"{where}:{label}",
                f"unknown key {key!r} (silently ignored at run time)",
                details={"key": key, "known": sorted(known)}))

    check(data.get("campaign", {}), _CAMPAIGN_KEYS, "campaign")
    check(data.get("defaults", {}), _DEFAULTS_KEYS, "defaults")
    for i, entry in enumerate(data.get("job", []) or []):
        check(entry, _JOB_KEYS, f"job[{i}]")
    for i, entry in enumerate(data.get("sweep", []) or []):
        check(entry, _SWEEP_KEYS, f"sweep[{i}]")
    return findings


def lint_spec_file(path: str | Path) -> list[Finding]:
    """All spec findings for one file: parse, vocabulary, divisibility,
    fingerprint identity."""
    from tpu_matmul_bench.campaign.spec import (
        CampaignSpecError,
        _parse_toml,
        spec_from_dict,
    )

    p = Path(path)
    where = str(p)
    try:
        text = p.read_text()
    except OSError as e:
        return [Finding("SPEC-001", where, f"cannot read spec: {e}")]

    try:
        if p.suffix == ".toml":
            data = _parse_toml(text)
        else:
            data = json.loads(text)
    except (CampaignSpecError, ValueError) as e:
        return [Finding("SPEC-001", where, f"spec does not parse: {e}")]
    if not isinstance(data, dict):
        return [Finding("SPEC-001", where,
                        f"spec root must be a table, got {type(data).__name__}")]

    # a standalone tenant-definition file (root is exactly [tenants.*]):
    # not a campaign spec at all — lint the tenant blocks and stop
    if set(data) == {"tenants"}:
        return _lint_tenants_data(data, where)

    # a chaos matrix (root is exactly [chaos]): the fault-injection
    # audit's spec, not a campaign — validate its cells and stop before
    # SPEC-001/002 fire on a vocabulary it never claimed to speak
    if set(data) == {"chaos"}:
        from tpu_matmul_bench.faults.audit import lint_chaos_data

        return lint_chaos_data(data, where)

    # a perf-observatory detection-window spec (root is exactly
    # [history], e.g. specs/history.toml): vocabulary + value ranges for
    # `obs detect`, not a campaign
    if set(data) == {"history"}:
        from tpu_matmul_bench.obs.detect import lint_history_data

        return lint_history_data(data, where)

    findings = _unknown_key_findings(data, where)

    try:
        spec = spec_from_dict(data)
    except CampaignSpecError as e:
        findings.append(Finding("SPEC-001", where, str(e)))
        return findings

    # fingerprint identity: the resume journal keys on fingerprints, so two
    # jobs sharing one means the second silently reuses the first's result
    by_fp: dict[str, str] = {}
    for job in spec.jobs:
        prior = by_fp.setdefault(job.fingerprint, job.job_id)
        if prior != job.job_id:
            findings.append(Finding(
                "SPEC-004", f"{where}:{job.job_id}",
                f"fingerprint {job.fingerprint} collides with job "
                f"{prior!r} — identical program+argv, one resume slot",
                details={"fingerprint": job.fingerprint,
                         "jobs": [prior, job.job_id]}))

    # serve jobs: subcommand + flag vocabulary + mix/grid/load/tenant
    # validation
    for job in spec.jobs:
        if job.program == "serve":
            findings.extend(_lint_serve_job(job, f"{where}:{job.job_id}",
                                            spec_dir=p.parent))
        elif job.program == "obs":
            findings.extend(_lint_obs_job(job, f"{where}:{job.job_id}"))
        elif job.program == "train":
            findings.extend(_lint_train_job(job, f"{where}:{job.job_id}"))

    # SPEC-007: --comm-quant wire-format validity, statically — the value
    # must parse against the wire-format grammar, and for block formats
    # the block (and the quantized ring's chunking) must divide every
    # payload the job's (mode, size, num_devices) cells imply; at run
    # time that ValueError fires an hour into the sweep
    for job in spec.jobs:
        findings.extend(_comm_quant_findings(job, f"{where}:{job.job_id}"))

    # SPEC-008: the hierarchical-mesh flag family (--mesh, per-link
    # --comm-quant, --stream-k, --mem-budget-gib), same
    # fail-at-lint-not-mid-campaign contract
    for job in spec.jobs:
        findings.extend(_hier_findings(job, f"{where}:{job.job_id}"))

    # SPEC-010: pod serving jobs — replica-group divisibility against
    # the mesh factorization + per-group wire formats over the mix
    for job in spec.jobs:
        if job.program == "serve":
            findings.extend(_pod_findings(job, f"{where}:{job.job_id}"))

    # mesh divisibility: sharding modes need size % num_devices == 0
    for job in spec.jobs:
        argv = list(job.argv)
        modes = _flag_values(argv, "--mode") or []
        if not (_DIVISIBILITY_MODES & set(modes)):
            continue
        devs = _flag_values(argv, "--num-devices")
        sizes = _flag_values(argv, "--sizes")
        for d_str in devs:
            for s_str in sizes:
                try:
                    d, s = int(d_str), int(s_str)
                except ValueError:
                    continue
                if d > 1 and s % d:
                    findings.append(Finding(
                        "SPEC-003", f"{where}:{job.job_id}",
                        f"size {s} not divisible by num_devices {d} for "
                        f"sharding mode(s) {sorted(_DIVISIBILITY_MODES & set(modes))}",
                        details={"size": s, "num_devices": d}))
    return findings


def lint_specs(paths: list[str]) -> list[Finding]:
    findings: list[Finding] = []
    for path in paths:
        findings.extend(lint_spec_file(path))
    return findings
