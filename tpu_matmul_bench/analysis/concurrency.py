"""Concurrency certifier: the CONC-* rule family (DESIGN §24).

The serving stack is genuinely threaded — load producers, per-group pod
drains, the obs exporter, the faults supervisor — and until this pass
its threading contracts (the FlightRecorder's "terminal() from any
thread under the lock, drain() worker-only" convention, the scheduler's
single-condition discipline, the obs registry's per-instrument locks)
were enforced only by docstrings and point tests. This module promotes
them to statically checked rules, the same contract as the jaxpr
auditor: parse, never execute.

The pass builds, from the AST of every file in scope:

- **thread roots** — `threading.Thread(target=...)` call sites (the
  target's terminal name is the root's role), plus the implicit `main`
  role for everything reachable from non-thread code, plus
  `ROLE_HINTS` declarations for functions the name-based call graph
  cannot see into (duck-typed receivers like `pool.get(...)`);
- **a call graph** — callee terminal names resolved against every
  in-scope definition of that name; `self.m()` resolves within the
  class when it defines `m`; names in `_OPAQUE_NAMES` (dict.get, list
  mutators, ...) never resolve, because a name-level graph would
  connect them to everything;
- **per-function access/lock facts** — `self.<attr>` writes and reads
  (including subscript stores and list/dict mutator calls), module
  globals rebound via `global`, the stack of lock-ish context managers
  held at each site, blocking calls, and wall-clock/unseeded-randomness
  call sites.

Rules (all error severity; stable IDs in `analysis/findings.RULES`):

- **CONC-001** — a shared mutable attribute or module global written
  from ≥2 thread roots (main counts: it is a thread) with no common
  guarding lock across all of its write/read sites.
- **CONC-002** — a cycle in the lock-acquisition-order graph: lock B
  acquired while A is held on one path and A while B is held on
  another — two threads interleaving those paths deadlock.
- **CONC-003** — a declared appender surface (`THREAD_ROLES`) called
  from a thread role outside its declaration, or — on the real tree —
  an appender-shaped method (`write_raw`/`drain`/`write_once`) shipped
  with no declaration at all. This generalizes the FlightRecorder
  sole-JsonWriter-toucher convention and the FAULT-002 writer registry
  into one checked contract.
- **CONC-004** — a blocking call (fsync, subprocess, `time.sleep`, AOT
  compile/serialize) issued while a lock is held: every other thread
  contending that lock stalls behind the syscall on the serve hot
  path.
- **CONC-005** — wall-clock (`time.time`, `datetime.now`) or unseeded
  randomness (module-level `random.*`) reachable from a fault-plan
  replay root: the chaos certifier's converged-state verdict assumes
  the replayed workload is a pure function of (plan, seed).

Known limits of the static approximation (also DESIGN §24): the call
graph is name-based, so `_OPAQUE_NAMES` receivers need `ROLE_HINTS`;
lock identity is `Class.attr` textual, so two instances of one class
share a node; blocking detection is direct-call only (a lock wrapper
that serializes an fsyncing writer — `_LockedStream` — is an accepted
serialization point); and TOCTOU races across two separately-guarded
reads are below this pass's resolution (the threaded stress tests in
tests/test_concurrency.py own that layer).

Everything here is stdlib-only and jax-free: the audit must run from
`lint` on machines without a backend, in well under a second.
"""

from __future__ import annotations

import ast
import dataclasses
import sys
import tempfile
from pathlib import Path
from typing import Any, Iterable

from tpu_matmul_bench.analysis.findings import Finding

# --------------------------------------------------------------------------
# shipped declaration tables — the checked threading model of this tree

#: directories the real-tree pass certifies (the threaded stack); a
#: fixture tree injected via `root=` is scanned whole.
SCOPE_DIRS = ("serve", "obs", "faults")

#: Appender surfaces and the thread roles allowed to touch them.
#: Key: "<rel>::<Class>.<method>"; value: allowed role names, where a
#: role is a thread target's terminal name, "main" is always allowed
#: (setup/teardown run there), and "*" admits any role. Declaring a
#: surface makes cross-role touches a CONC-003 error; shipping an
#: appender-shaped method with NO declaration is also CONC-003 on the
#: real tree, so this table cannot silently rot.
THREAD_ROLES: dict[str, tuple[str, ...]] = {
    # the PR-16 convention, now checked: terminal() buffers from any
    # thread under the recorder lock; only the worker drains to the
    # JsonWriter (one fsyncing appender per ledger).
    "serve/trace.py::FlightRecorder.terminal": ("*",),
    "serve/trace.py::FlightRecorder.drain": ("_worker_drain",),
    # the pod ledger door: G group drains funnel through one lock
    # wrapper; nothing else may write the shared stream.
    "serve/pod.py::_LockedStream.write_raw": ("_worker_drain",),
    # the obs snapshot appender: the exporter loop owns the file;
    # `run_obs` (the faults chaos workload) drives it from main.
    "obs/export.py::SnapshotExporter.write_once": ("_loop",),
    # class-level declaration (no method suffix): the per-group AOT
    # executable cache is a phase-separated handoff, not concurrent
    # state — main warm-starts it before the group's drain thread
    # exists, then exactly one drain touches it until the join. A
    # class-level entry exempts the class from CONC-001 and records
    # the convention where the next refactor will trip over it.
    "serve/cache.py::ExecutableCache": ("main", "_worker_drain"),
}

#: Reach declarations for functions the name-based call graph is blind
#: to — their callers invoke them through `_OPAQUE_NAMES` receivers
#: (`cache.get(...)`, `pool.get(...)`), so the BFS cannot discover the
#: thread roles that actually run them. Each entry seeds the role BFS
#: at that function. An entry here is a statement of the threading
#: model, exactly like a docstring's "one worker thread touches this" —
#: except CONC-001 now holds the code to it.
ROLE_HINTS: dict[str, tuple[str, ...]] = {
    # per-group operand views: device_put memoization on the group
    # drain thread after a main-thread warm start. (The executable
    # cache itself is a class-level THREAD_ROLES handoff declaration —
    # see above — because its touches are phase-separated, not locked.)
    "serve/pod.py::_GroupOperandPool.get": ("main", "_worker_drain"),
}

#: Fault-plan replay roots: the resumable chaos workloads and the cell
#: driver. Everything statically reachable from these must be a pure
#: function of (plan, seed) — CONC-005 polices wall-clock/randomness.
REPLAY_ROOTS = ("run_cell", "run_audit", "run_ledger", "run_tune",
                "run_obs")

#: Wall-clock sites reachable from replay that are NOT determinism
#: hazards, with the reason (the FAULT-001 SPAWN_ALLOWLIST pattern:
#: an allowlist entry is a reviewed claim, and a stale entry is itself
#: a finding via the selftest's table checks).
REPLAY_CLOCK_ALLOWLIST: dict[str, str] = {
    "faults/supervisor.py":
        "heartbeat staleness compares wall clock against the heartbeat "
        "file's mtime — both sides are wall-clock, and replay checks "
        "the stall verdict, never the stamp",
    "obs/export.py":
        "snapshot ts_unix / flush-age stamps are observability "
        "metadata; the chaos certifier's convergence compare excludes "
        "manifests and timestamps",
    "obs/context.py":
        "uuid4 mints the process run id — identity in manifests, not "
        "replayed state; TPU_BENCH_RUN_ID pins it when a spawner needs "
        "the child to BE a specific run, and convergence compares "
        "exclude manifests",
}

# --------------------------------------------------------------------------
# pattern tables

#: a context-manager expression whose terminal name matches this is a
#: lock acquisition (Lock, RLock, Condition, module-level *_LOCK, ...)
_LOCK_NAME_PARTS = ("lock", "cond", "mutex", "rlock", "semaphore")

#: method names the call graph never resolves: they are stdlib-common
#: (dict.get, list.append, re.match, ...) and a name-level graph would
#: connect every `.get(...)` to every in-scope `def get`.
_OPAQUE_NAMES = frozenset({
    "get", "put", "items", "keys", "values", "append", "appendleft",
    "add", "update", "pop", "popleft", "setdefault", "close", "read",
    "write", "copy", "sort", "join", "start", "run", "set", "clear",
    "count", "index", "open", "search", "match", "group", "groups",
    "split", "rsplit", "strip", "encode", "decode", "acquire",
    "release", "wait", "notify", "notify_all", "touch", "exists",
    "mkdir", "stat", "poll", "kill", "send", "recv", "extend",
    "remove", "discard", "insert", "flush", "seek", "tell", "format",
    "replace", "lower", "upper", "startswith", "endswith", "fileno",
    "is_set", "is_alive", "move_to_end", "total_seconds", "as_posix",
    "resolve", "glob", "rglob", "relative_to", "print",
})

#: mutator method names on `self.<attr>` that count as writes to the
#: attribute's contents (the FlightRecorder `_pending.append` shape)
_MUTATOR_NAMES = frozenset({
    "append", "appendleft", "extend", "add", "insert", "pop", "popleft",
    "update", "setdefault", "clear", "remove", "discard", "sort",
    "move_to_end",
})

#: methods written only here are construction, not shared-state writes
_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

#: appender-shaped method names that MUST carry a THREAD_ROLES entry
#: on the real tree (the CONC-003 coverage leg)
_APPENDER_NAMES = frozenset({"write_raw", "drain", "write_once"})

#: (receiver, name) shapes that block the calling thread; receiver ""
#: matches any. `re.compile` is excluded by the receiver test.
_BLOCKING_CALLS: tuple[tuple[str, str, str], ...] = (
    ("os", "fsync", "fsync"),
    ("time", "sleep", "time.sleep"),
    ("subprocess", "", "subprocess"),
    ("", "serialize_executable", "AOT serialize"),
    ("", "deserialize_and_load", "AOT deserialize"),
    ("", "compile", "AOT compile"),
)

#: (receiver, name) shapes that read the wall clock or unseeded
#: randomness — the CONC-005 determinism hazards. `random.Random(seed)`
#: instances are deliberately absent: their draws replay.
_CLOCK_CALLS: tuple[tuple[str, str, str], ...] = (
    ("time", "time", "time.time"),
    ("datetime", "now", "datetime.now"),
    ("datetime.datetime", "now", "datetime.now"),
    ("random", "random", "random.random"),
    ("random", "randint", "random.randint"),
    ("random", "randrange", "random.randrange"),
    ("random", "choice", "random.choice"),
    ("random", "shuffle", "random.shuffle"),
    ("random", "uniform", "random.uniform"),
    ("random", "gauss", "random.gauss"),
    ("uuid", "uuid4", "uuid.uuid4"),
)

_MAIN_ROLE = "main"


def _is_lock_name(term: str) -> bool:
    low = term.lower()
    return any(part in low for part in _LOCK_NAME_PARTS)


def _dotted(node: ast.AST) -> str:
    """Best-effort dotted source of a Name/Attribute chain ('' if the
    expression is not a plain chain)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    if isinstance(node, ast.Call):
        inner = _dotted(node.func)
        return f"{inner}()" if inner else ""
    return ""


# --------------------------------------------------------------------------
# per-function facts


@dataclasses.dataclass
class _Access:
    """One read/write of shared state, with the locks held at the site."""

    key: tuple[str, ...]  # ("attr", rel, Class, name) | ("global", rel, name)
    kind: str  # "write" | "read"
    lineno: int
    locks: frozenset[str]  # terminal lock names held


@dataclasses.dataclass
class _Call:
    name: str  # callee terminal name
    recv: str  # dotted receiver ("" for a bare call)
    lineno: int
    locks: frozenset[str]  # class-qualified lock nodes held


@dataclasses.dataclass
class _Func:
    qual: str  # "rel::Class.meth" | "rel::func"
    rel: str
    cls: str | None
    name: str
    lineno: int
    accesses: list[_Access] = dataclasses.field(default_factory=list)
    calls: list[_Call] = dataclasses.field(default_factory=list)
    acquires: set[str] = dataclasses.field(default_factory=set)
    blocking: list[tuple[str, int, frozenset]] = dataclasses.field(
        default_factory=list)
    clocks: list[tuple[str, int]] = dataclasses.field(default_factory=list)
    thread_targets: list[str] = dataclasses.field(default_factory=list)
    globals_declared: set[str] = dataclasses.field(default_factory=set)


class _FuncVisitor(ast.NodeVisitor):
    """Walks ONE function body tracking the held-lock stack; nested
    function defs are indexed separately by the module scan and skipped
    here (their bodies run on whatever thread calls them, which the
    call graph models), but lambda bodies are inlined."""

    def __init__(self, func: _Func) -> None:
        self.f = func
        self._lock_stack: list[str] = []  # class-qualified nodes

    # -- lock bookkeeping ---------------------------------------------------

    def _lock_node(self, expr: ast.AST) -> str | None:
        dotted = _dotted(expr)
        if not dotted:
            return None
        term = dotted.split(".")[-1].replace("()", "")
        if not _is_lock_name(term):
            return None
        if dotted.startswith("self.") and self.f.cls:
            return f"{self.f.cls}.{term}"
        return f"{self.f.rel}:{dotted}"

    def _held(self) -> frozenset[str]:
        return frozenset(self._lock_stack)

    def _held_terms(self) -> frozenset[str]:
        return frozenset(n.rsplit(".", 1)[-1].rsplit(":", 1)[-1]
                         for n in self._lock_stack)

    def visit_With(self, node: ast.With) -> None:
        self._with(node)

    def visit_AsyncWith(self, node: ast.AsyncWith) -> None:
        self._with(node)

    def _with(self, node: Any) -> None:
        acquired: list[str] = []
        for item in node.items:
            lock = self._lock_node(item.context_expr)
            if lock is not None:
                # a lock acquired while others are held orders after
                # every one of them
                self.f.acquires.add(lock)
                acquired.append(lock)
                self._lock_stack.append(lock)
            self.visit(item.context_expr)
        for stmt in node.body:
            self.visit(stmt)
        for _ in acquired:
            self._lock_stack.pop()

    # -- shared-state accesses ---------------------------------------------

    def _attr_key(self, node: ast.AST) -> tuple[str, ...] | None:
        """('attr', rel, Class, name) for a `self.<name>` chain head."""
        while isinstance(node, ast.Subscript):
            node = node.value
        if (isinstance(node, ast.Attribute)
                and isinstance(node.value, ast.Name)
                and node.value.id == "self" and self.f.cls):
            return ("attr", self.f.rel, self.f.cls, node.attr)
        return None

    def _record(self, key: tuple[str, ...] | None, kind: str,
                lineno: int) -> None:
        if key is not None:
            self.f.accesses.append(
                _Access(key, kind, lineno, self._held_terms()))

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            self._target(tgt, node.lineno)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._target(node.target, node.lineno)
        # an augmented assign also reads
        self._record(self._attr_key(node.target), "read", node.lineno)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._target(node.target, node.lineno)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            self._target(tgt, node.lineno)

    def _target(self, tgt: ast.AST, lineno: int) -> None:
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._target(el, lineno)
            return
        self._record(self._attr_key(tgt), "write", lineno)
        if (isinstance(tgt, ast.Name)
                and tgt.id in self.f.globals_declared):
            self.f.accesses.append(_Access(
                ("global", self.f.rel, tgt.id), "write", lineno,
                self._held_terms()))

    def visit_Global(self, node: ast.Global) -> None:
        self.f.globals_declared.update(node.names)

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            self._record(self._attr_key(node), "read", node.lineno)
        self.generic_visit(node)

    def visit_Name(self, node: ast.Name) -> None:
        if (isinstance(node.ctx, ast.Load)
                and node.id in self.f.globals_declared):
            self.f.accesses.append(_Access(
                ("global", self.f.rel, node.id), "read", node.lineno,
                self._held_terms()))

    # -- calls --------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        dotted = _dotted(node.func)
        recv, _, name = dotted.rpartition(".")
        if not name:
            name = dotted
        if name:
            self.f.calls.append(
                _Call(name, recv, node.lineno, self._held()))
            # self.<attr>.append(...) mutates the attribute's contents
            if (name in _MUTATOR_NAMES
                    and isinstance(node.func, ast.Attribute)):
                self._record(self._attr_key(node.func.value), "write",
                             node.lineno)
            for brecv, bname, desc in _BLOCKING_CALLS:
                if ((bname == "" or bname == name)
                        and (brecv == "" or recv == brecv
                             or recv.startswith(brecv + "."))
                        and not (name == "compile" and recv == "re")
                        and (bname or recv.split(".")[0] == brecv)):
                    if self._lock_stack:
                        self.f.blocking.append(
                            (desc, node.lineno, self._held()))
                    break
            for crecv, cname, desc in _CLOCK_CALLS:
                if name == cname and (recv == crecv
                                      or recv.endswith("." + crecv)):
                    self.f.clocks.append((desc, node.lineno))
                    break
            if name == "Thread" and recv in ("threading", ""):
                for kw in node.keywords:
                    if kw.arg == "target":
                        tgt = _dotted(kw.value)
                        if tgt:
                            self.f.thread_targets.append(
                                tgt.split(".")[-1])
        if isinstance(node.func, ast.Attribute):
            self.visit(node.func.value)  # receiver reads (self.x.m())
        for arg in node.args:
            self.visit(arg)
        for kw in node.keywords:
            self.visit(kw.value)

    # nested defs are indexed as their own _Func by the module scan
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        pass

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        pass

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)


# --------------------------------------------------------------------------
# tree model


@dataclasses.dataclass
class _Tree:
    funcs: dict[str, _Func]  # qual -> facts
    by_name: dict[str, list[str]]  # terminal name -> [qual, ...]
    thread_targets: list[tuple[str, str, int]]  # (target, rel, lineno)
    appender_defs: list[str]  # quals of appender-shaped methods


def _scope_files(root: Path, real_tree: bool) -> list[Path]:
    if not real_tree:
        return sorted(root.rglob("*.py"))
    files: list[Path] = []
    for d in SCOPE_DIRS:
        files.extend((root / d).rglob("*.py"))
    return sorted(files)


def _index_tree(root: Path, real_tree: bool) -> _Tree:
    funcs: dict[str, _Func] = {}
    by_name: dict[str, list[str]] = {}
    threads: list[tuple[str, str, int]] = []
    appenders: list[str] = []

    def walk_body(body: Iterable[ast.stmt], rel: str,
                  cls: str | None) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (f"{rel}::{cls}.{node.name}" if cls
                        else f"{rel}::{node.name}")
                f = _Func(qual, rel, cls, node.name, node.lineno)
                # collect `global` declarations first: the visitor needs
                # them before it sees the assignments
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Global):
                        f.globals_declared.update(sub.names)
                v = _FuncVisitor(f)
                for stmt in node.body:
                    v.visit(stmt)
                funcs[qual] = f
                by_name.setdefault(node.name, []).append(qual)
                for tgt in f.thread_targets:
                    threads.append((tgt, rel, node.lineno))
                if cls and node.name in _APPENDER_NAMES:
                    appenders.append(qual)
                walk_body(node.body, rel, cls)  # nested defs
            elif isinstance(node, ast.ClassDef):
                walk_body(node.body, rel, node.name)

    for path in _scope_files(root, real_tree):
        rel = path.relative_to(root).as_posix()
        try:
            tree = ast.parse(path.read_text(errors="replace"))
        except (OSError, SyntaxError):
            continue
        walk_body(tree.body, rel, None)

    for qual in sorted(by_name, key=lambda n: n):
        by_name[qual].sort()
    return _Tree(funcs, by_name, sorted(threads), sorted(appenders))


def _resolve(tree: _Tree, caller: _Func, call: _Call) -> list[str]:
    """Callee quals for one call site (the name-based approximation)."""
    if call.name in _OPAQUE_NAMES:
        return []
    if call.recv == "self" and caller.cls:
        own = f"{caller.rel}::{caller.cls}.{call.name}"
        if own in tree.funcs:
            return [own]
    cands = tree.by_name.get(call.name, [])
    if call.recv in ("", None):
        # a bare call prefers same-module definitions
        same = [q for q in cands if tree.funcs[q].rel == caller.rel
                and tree.funcs[q].cls is None]
        if same:
            return same
    return list(cands)


def _reach(tree: _Tree, seeds: Iterable[str]) -> set[str]:
    seen: set[str] = set()
    frontier = [q for q in seeds if q in tree.funcs]
    while frontier:
        qual = frontier.pop()
        if qual in seen:
            continue
        seen.add(qual)
        f = tree.funcs[qual]
        for call in f.calls:
            for callee in _resolve(tree, f, call):
                if callee not in seen:
                    frontier.append(callee)
    return seen


def _role_map(tree: _Tree,
              role_hints: dict[str, tuple[str, ...]]) -> dict[str, set[str]]:
    """qual -> set of thread roles whose dynamic extent can reach it."""
    seeds_by_role: dict[str, set[str]] = {}
    for target, _rel, _ln in tree.thread_targets:
        seeds_by_role.setdefault(target, set()).update(
            tree.by_name.get(target, []))
    for qual, roles in role_hints.items():
        for role in roles:
            if role != _MAIN_ROLE:
                seeds_by_role.setdefault(role, set()).add(qual)

    roles: dict[str, set[str]] = {q: set() for q in tree.funcs}
    thread_reach: set[str] = set()
    for role in sorted(seeds_by_role):
        reach = _reach(tree, sorted(seeds_by_role[role]))
        thread_reach.update(reach)
        for q in reach:
            roles[q].add(role)
    # main: everything reachable from functions no thread root reaches
    # (the main thread is the only thing left that can call them)
    main_seeds = sorted(q for q in tree.funcs if q not in thread_reach)
    for q in _reach(tree, main_seeds):
        roles[q].add(_MAIN_ROLE)
    for qual, hinted in role_hints.items():
        if _MAIN_ROLE in hinted and qual in roles:
            roles[qual].add(_MAIN_ROLE)
    return roles


# --------------------------------------------------------------------------
# the rules


def _lock_terms(nodes: Iterable[str]) -> frozenset[str]:
    return frozenset(n.rsplit(".", 1)[-1].rsplit(":", 1)[-1]
                     for n in nodes)


def _inherited_locks(tree: _Tree) -> dict[str, frozenset[str]]:
    """Lock tokens guaranteed held at EVERY static call site of each
    function — the `_collect_locked` convention, checked: a helper only
    ever invoked under the caller's lock inherits that guard at its
    access sites. Meet over call sites, iterated so a locked helper's
    own helpers inherit too; a function with no static callers (an
    entry point) inherits nothing."""
    callers: dict[str, list[tuple[str, frozenset[str]]]] = {}
    for qual in sorted(tree.funcs):
        f = tree.funcs[qual]
        for call in f.calls:
            for callee in _resolve(tree, f, call):
                callers.setdefault(callee, []).append(
                    (qual, _lock_terms(call.locks)))
    inherited: dict[str, frozenset[str]] = {
        q: frozenset() for q in tree.funcs}
    for _ in range(8):  # bounded: chains this deep don't exist here
        changed = False
        for qual in sorted(callers):
            sets = [held | inherited[caller]
                    for caller, held in callers[qual]]
            meet = frozenset.intersection(*sets)
            if meet != inherited[qual]:
                inherited[qual] = meet
                changed = True
        if not changed:
            break
    return inherited


def _conc001(tree: _Tree, roles: dict[str, set[str]],
             thread_roles: dict[str, tuple[str, ...]],
             inherited: dict[str, frozenset[str]]) -> list[Finding]:
    declared_single = set()
    for key in thread_roles:
        rel_cls = key.split("::", 1)
        if len(rel_cls) == 2:
            rel, tail = rel_cls
            declared_single.add((rel, tail.split(".")[0]))

    by_key: dict[tuple[str, ...], list[tuple[_Access, _Func]]] = {}
    for qual in sorted(tree.funcs):
        f = tree.funcs[qual]
        for acc in f.accesses:
            by_key.setdefault(acc.key, []).append((acc, f))

    findings: list[Finding] = []
    for key in sorted(by_key):
        sites = by_key[key]
        writes = [(a, f) for a, f in sites
                  if a.kind == "write" and f.name not in _INIT_METHODS]
        if not writes:
            continue
        if key[0] == "attr" and (key[1], key[2]) in declared_single:
            continue  # declared sole-toucher class; CONC-003 owns it
        write_roles: set[str] = set()
        for _a, f in writes:
            write_roles.update(roles.get(f.qual, {_MAIN_ROLE})
                               or {_MAIN_ROLE})
        if len(write_roles) < 2:
            continue
        # every write AND read outside construction must share a guard
        # (held at the site, or inherited from all callers — the
        # `_collect_locked` convention)
        checked = [(a, f) for a, f in sites
                   if f.name not in _INIT_METHODS]
        common = frozenset.intersection(
            *[a.locks | inherited[f.qual] for a, f in checked]) \
            if checked else frozenset()
        if common:
            continue
        a0, f0 = min(writes, key=lambda s: (s[1].rel, s[0].lineno))
        if key[0] == "attr":
            what = f"{key[2]}.{key[3]}"
        else:
            what = f"module global {key[2]!r}"
        bare = sorted({f"{f.rel}:{a.lineno}" for a, f in checked
                       if not (a.locks | inherited[f.qual])})
        findings.append(Finding(
            "CONC-001", f"{f0.rel}:{a0.lineno}",
            f"shared mutable state {what} is written from thread roles "
            f"{{{', '.join(sorted(write_roles))}}} with no common "
            f"guarding lock — unguarded site(s): {', '.join(bare[:4])}",
            details={"state": what, "roles": sorted(write_roles),
                     "unguarded_sites": bare}))
    return findings


def _lock_graph(tree: _Tree) -> dict[str, set[tuple[str, str]]]:
    """lock -> {(lock acquired while held, witness site)}. Edges come
    from lexically nested `with` blocks and from calls made while a
    lock is held into functions that (transitively) acquire."""
    # transitive acquisition sets, fixpoint over the call graph
    acq: dict[str, set[str]] = {
        q: set(tree.funcs[q].acquires) for q in tree.funcs}
    changed = True
    guard = 0
    while changed and guard < 50:
        changed = False
        guard += 1
        for qual in sorted(tree.funcs):
            f = tree.funcs[qual]
            for call in f.calls:
                for callee in _resolve(tree, f, call):
                    extra = acq[callee] - acq[qual]
                    if extra:
                        acq[qual].update(extra)
                        changed = True

    edges: dict[str, set[tuple[str, str]]] = {}
    for qual in sorted(tree.funcs):
        f = tree.funcs[qual]
        for call in f.calls:
            if not call.locks:
                continue
            inner: set[str] = set()
            for callee in _resolve(tree, f, call):
                inner.update(acq[callee])
            for held in sorted(call.locks):
                for got in sorted(inner - {held}):
                    edges.setdefault(held, set()).add(
                        (got, f"{f.rel}:{call.lineno}"))
    return edges


def _conc002(tree: _Tree, root: Path, real_tree: bool) -> list[Finding]:
    edges = _lock_graph(tree)
    # add direct with-nesting edges (re-walk: _Func drops its AST)
    for path in _scope_files(root, real_tree):
        rel = path.relative_to(root).as_posix()
        try:
            mod = ast.parse(path.read_text(errors="replace"))
        except (OSError, SyntaxError):
            continue
        _collect_nested_with(mod, rel, edges)

    graph = {src: sorted({dst for dst, _w in dsts})
             for src, dsts in edges.items()}
    witness = {}
    for src, dsts in edges.items():
        for dst, site in sorted(dsts):
            witness.setdefault((src, dst), site)

    findings: list[Finding] = []
    seen_cycles: set[tuple[str, ...]] = set()
    for start in sorted(graph):
        cycle = _find_cycle(graph, start)
        if not cycle:
            continue
        canon = _canon_cycle(cycle)
        if canon in seen_cycles:
            continue
        seen_cycles.add(canon)
        hops = " -> ".join(canon + (canon[0],))
        sites = sorted({witness.get((a, b), "?")
                        for a, b in zip(canon, canon[1:] + (canon[0],))})
        findings.append(Finding(
            "CONC-002", sites[0] if sites else canon[0],
            f"lock-order cycle {hops}: two threads taking these locks "
            "in opposite orders deadlock",
            details={"cycle": list(canon), "witness_sites": sites}))
    return findings


def _collect_nested_with(mod: ast.Module, rel: str,
                         edges: dict[str, set[tuple[str, str]]]) -> None:
    def walk(body: Iterable[ast.stmt], cls: str | None,
             stack: list[str]) -> None:
        for node in body:
            if isinstance(node, ast.ClassDef):
                walk(node.body, node.name, [])
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                walk(node.body, cls, [])
            elif isinstance(node, (ast.With, ast.AsyncWith)):
                shim = _Func(f"{rel}::<with>", rel, cls, "<with>",
                             node.lineno)
                helper = _FuncVisitor(shim)
                got: list[str] = []
                for item in node.items:
                    lock = helper._lock_node(item.context_expr)
                    if lock is not None:
                        for outer in stack:
                            if outer != lock:
                                edges.setdefault(outer, set()).add(
                                    (lock, f"{rel}:{node.lineno}"))
                        stack.append(lock)
                        got.append(lock)
                walk(node.body, cls, stack)
                for _ in got:
                    stack.pop()
            else:
                for field in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(node, field, None)
                    if sub:
                        inner = [getattr(h, "body", h) for h in sub] \
                            if field == "handlers" else [sub]
                        for blk in inner:
                            walk(blk, cls, stack)

    walk(mod.body, None, [])


def _find_cycle(graph: dict[str, list[str]],
                start: str) -> tuple[str, ...] | None:
    path: list[str] = []
    on_path: set[str] = set()
    done: set[str] = set()

    def dfs(node: str) -> tuple[str, ...] | None:
        if node in on_path:
            i = path.index(node)
            return tuple(path[i:])
        if node in done:
            return None
        path.append(node)
        on_path.add(node)
        for nxt in graph.get(node, []):
            got = dfs(nxt)
            if got:
                return got
        path.pop()
        on_path.discard(node)
        done.add(node)
        return None

    return dfs(start)


def _canon_cycle(cycle: tuple[str, ...]) -> tuple[str, ...]:
    i = cycle.index(min(cycle))
    return cycle[i:] + cycle[:i]


def _conc003(tree: _Tree, roles: dict[str, set[str]],
             thread_roles: dict[str, tuple[str, ...]],
             real_tree: bool) -> list[Finding]:
    findings: list[Finding] = []
    declared_methods: dict[tuple[str | None, str], str] = {}
    for key, allowed in sorted(thread_roles.items()):
        rel, _, tail = key.partition("::")
        if "." not in tail:
            # class-level handoff declaration (CONC-001 exemption);
            # there is no single method surface to police call sites on
            continue
        cls, _, meth = tail.rpartition(".")
        declared_methods[(cls or None, meth)] = key

    for qual in sorted(tree.funcs):
        f = tree.funcs[qual]
        for call in f.calls:
            # match declared surfaces by method name (+ class when the
            # receiver is self)
            for (cls, meth), key in declared_methods.items():
                if call.name != meth:
                    continue
                allowed = thread_roles[key]
                if "*" in allowed:
                    continue
                srel, _, stail = key.partition("::")
                # the surface's own class may call itself
                if f.rel == srel and f.cls and stail.startswith(
                        f.cls + "."):
                    continue
                caller_roles = roles.get(qual, set()) or {_MAIN_ROLE}
                bad = sorted(caller_roles
                             - set(allowed) - {_MAIN_ROLE})
                if bad:
                    findings.append(Finding(
                        "CONC-003", f"{f.rel}:{call.lineno}",
                        f"appender surface {key} touched from thread "
                        f"role(s) {{{', '.join(bad)}}} — its declared "
                        f"sole toucher is "
                        f"{{{', '.join(allowed)}}} (THREAD_ROLES)",
                        details={"surface": key,
                                 "caller_roles": sorted(caller_roles),
                                 "allowed": list(allowed)}))
                break
    if real_tree:
        declared_quals = {k.replace("::", "::") for k in thread_roles}
        for qual in tree.appender_defs:
            rel, _, tail = qual.partition("::")
            if f"{rel}::{tail}" not in declared_quals:
                findings.append(Finding(
                    "CONC-003", rel,
                    f"appender-shaped method {qual} has no THREAD_ROLES "
                    "declaration — every write_raw/drain/write_once "
                    "surface must declare its sole toucher",
                    details={"surface": qual}))
    return findings


def _conc004(tree: _Tree) -> list[Finding]:
    findings: list[Finding] = []
    for qual in sorted(tree.funcs):
        f = tree.funcs[qual]
        for desc, lineno, locks in f.blocking:
            findings.append(Finding(
                "CONC-004", f"{f.rel}:{lineno}",
                f"blocking call ({desc}) while holding "
                f"{{{', '.join(sorted(locks))}}} — every thread "
                "contending the lock stalls behind the syscall on the "
                "serve hot path",
                details={"blocking": desc,
                         "locks": sorted(locks)}))
    return findings


def _conc005(tree: _Tree, replay_roots: tuple[str, ...],
             clock_allowlist: dict[str, str]) -> list[Finding]:
    seeds: list[str] = []
    for name in replay_roots:
        seeds.extend(tree.by_name.get(name, []))
    reach = _reach(tree, sorted(seeds))
    findings: list[Finding] = []
    for qual in sorted(reach):
        f = tree.funcs[qual]
        if f.rel in clock_allowlist:
            continue
        for desc, lineno in f.clocks:
            findings.append(Finding(
                "CONC-005", f"{f.rel}:{lineno}",
                f"{desc} reachable from fault-plan replay root(s) — "
                "the chaos certifier's converged-state verdict assumes "
                "replay is a pure function of (plan, seed); use "
                "time.monotonic for intervals or a seeded "
                "random.Random",
                details={"call": desc, "function": qual}))
    return findings


# --------------------------------------------------------------------------
# entry points


def _package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def conc_findings(
    root: str | Path | None = None, *,
    thread_roles: dict[str, tuple[str, ...]] | None = None,
    role_hints: dict[str, tuple[str, ...]] | None = None,
    replay_roots: tuple[str, ...] | None = None,
    clock_allowlist: dict[str, str] | None = None,
) -> list[Finding]:
    """CONC-001..005 over the tree (package serve/obs/faults by
    default; tests inject seeded fixture trees plus their own
    declaration tables). Deterministic: findings sort by (rule, where,
    message), so two runs on one tree are byte-identical."""
    real_tree = root is None
    base = Path(root) if root is not None else _package_root()
    t_roles = THREAD_ROLES if thread_roles is None else thread_roles
    hints = ROLE_HINTS if role_hints is None else role_hints
    r_roots = REPLAY_ROOTS if replay_roots is None else replay_roots
    allow = (REPLAY_CLOCK_ALLOWLIST if clock_allowlist is None
             else clock_allowlist)

    tree = _index_tree(base, real_tree)
    roles = _role_map(tree, hints)
    inherited = _inherited_locks(tree)
    findings: list[Finding] = []
    findings.extend(_conc001(tree, roles, t_roles, inherited))
    findings.extend(_conc002(tree, base, real_tree))
    findings.extend(_conc003(tree, roles, t_roles, real_tree))
    findings.extend(_conc004(tree))
    findings.extend(_conc005(tree, tuple(r_roots), allow))
    return sorted(findings, key=lambda f: (f.rule, f.where, f.message))


# --------------------------------------------------------------------------
# selftest (lint_ci.sh layer 14)

_SELFTEST_FIXTURES: tuple[tuple[str, str, str], ...] = (
    # (rule expected, filename, source) — each fixture is the minimal
    # tree that must trip exactly its rule; the selftest also asserts
    # the repaired twin stays clean where one exists.
    ("CONC-001", "racy.py", """\
import threading

class Box:
    def __init__(self):
        self.n = 0
    def bump(self):
        self.n += 1
    def zero(self):
        self.n = 0

def t1(box):
    box.bump()

def t2(box):
    box.zero()

def main(box):
    threading.Thread(target=t1, args=(box,)).start()
    threading.Thread(target=t2, args=(box,)).start()
"""),
    ("CONC-002", "deadlock.py", """\
import threading

A_LOCK = threading.Lock()
B_LOCK = threading.Lock()

def fwd():
    with A_LOCK:
        with B_LOCK:
            pass

def rev():
    with B_LOCK:
        with A_LOCK:
            pass

def main():
    threading.Thread(target=fwd).start()
    threading.Thread(target=rev).start()
"""),
    ("CONC-004", "slowpath.py", """\
import threading
import time

class Hot:
    def __init__(self):
        self._lock = threading.Lock()
    def step(self):
        with self._lock:
            time.sleep(0.5)

def loop(h):
    h.step()

def main(h):
    threading.Thread(target=loop, args=(h,)).start()
"""),
    ("CONC-005", "replay.py", """\
import random
import time

def run_cell(plan):
    stamp = time.time()
    jitter = random.random()
    return stamp + jitter
"""),
)

_CONC003_FIXTURE = """\
import threading

class Ledger:
    def write_raw(self, rec):
        pass

def producer(led):
    led.write_raw({})

def main(led):
    threading.Thread(target=producer, args=(led,)).start()
"""


def run_conc_selftest() -> list[Any]:
    """`lint conc selftest`: (1) the real serve/obs/faults tree must
    certify clean, (2) each seeded CONC-001..005 fixture must trip
    exactly its rule, (3) two consecutive real-tree passes must render
    byte-identical findings, and (4) the shipped declaration tables
    must not have rotted (every THREAD_ROLES / ROLE_HINTS /
    REPLAY_CLOCK_ALLOWLIST entry names a surface that still exists).
    Exits nonzero on any violation."""
    from tpu_matmul_bench.utils.reporting import header, report

    problems: list[str] = []
    report(header("Concurrency lint selftest", {
        "Scope": ", ".join(SCOPE_DIRS),
        "Rules": "CONC-001..005",
        "Declared surfaces": str(len(THREAD_ROLES)),
    }))

    tree_findings = conc_findings()
    problems.extend(
        f"real tree: {f.rule} at {f.where}: {f.message}"
        for f in tree_findings)

    second = conc_findings()
    if [f.to_record() for f in second] != \
            [f.to_record() for f in tree_findings]:
        problems.append("nondeterministic findings: two consecutive "
                        "passes over one tree differ")

    with tempfile.TemporaryDirectory(prefix="conc-seeded-") as td:
        for rule, fname, src in _SELFTEST_FIXTURES:
            fdir = Path(td) / rule.lower()
            fdir.mkdir()
            (fdir / fname).write_text(src)
            got = conc_findings(fdir, thread_roles={}, role_hints={},
                                clock_allowlist={})
            rules = sorted({f.rule for f in got})
            if rule not in rules:
                problems.append(
                    f"seeded {rule} fixture did not fire (got {rules})")
        fdir = Path(td) / "conc-003"
        fdir.mkdir()
        (fdir / "appender.py").write_text(_CONC003_FIXTURE)
        got = conc_findings(
            fdir,
            thread_roles={"appender.py::Ledger.write_raw": ("drainer",)},
            role_hints={}, clock_allowlist={})
        if "CONC-003" not in {f.rule for f in got}:
            problems.append("seeded CONC-003 fixture did not fire")

    # table hygiene: an entry naming a vanished surface claims a
    # contract nobody ships
    pkg_tree = _index_tree(_package_root(), real_tree=True)
    for key in sorted(THREAD_ROLES) + sorted(ROLE_HINTS):
        rel, _, tail = key.partition("::")
        if "." not in tail:
            # class-level declaration: live iff any method of that
            # class exists in the scoped tree
            prefix = f"{rel}::{tail}."
            if not any(q.startswith(prefix) for q in pkg_tree.funcs):
                problems.append(f"stale declaration: {key} names a "
                                "class that no longer exists")
            continue
        if f"{rel}::{tail}" not in pkg_tree.funcs:
            problems.append(f"stale declaration: {key} names a surface "
                            "that no longer exists")
    scoped_rels = {f.rel for f in pkg_tree.funcs.values()}
    for rel in sorted(REPLAY_CLOCK_ALLOWLIST):
        if rel not in scoped_rels:
            problems.append(f"stale REPLAY_CLOCK_ALLOWLIST entry: {rel}")

    if problems:
        report(*[f"conc selftest FAILED: {p}" for p in problems],
               file=sys.stderr)
        raise SystemExit(1)
    report(f"conc selftest ok: real tree clean over {len(SCOPE_DIRS)} "
           f"scope dirs, {len(_SELFTEST_FIXTURES) + 1} seeded rules "
           "fire, findings deterministic, declaration tables live")
    return [f.to_record() for f in tree_findings]
