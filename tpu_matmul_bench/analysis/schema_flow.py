"""Schema-flow certifier: the SCHEMA-* rule family (DESIGN §25).

After nineteen PRs the repo writes about a dozen durable JSONL record
families — bench/serve/train ledgers, the serve_batch and serve_span
streams, the campaign journal, the tune DB, the artifact manifest, the
fault-audit log, obs snapshots, history points — and until this pass
each family's schema was enforced only by a hand-maintained validator
and whatever its consumers happened to read. That is the same drift
class the concurrency certifier closed for threading contracts: the
producer moves, the validator lags, and the first evidence is a
KeyError (or a silent None) in a gate an hour into a campaign.

This module promotes the producer/consumer contract to statically
checked rules, under the concurrency certifier's exact operating model:
parse, never execute, stdlib-only, jax-free. From the AST of every file
in scope it extracts

- **written keys** per family — string keys of dict literals, subscript
  stores (``rec["k"] = v``), ``dict(k=...)`` keywords, and
  ``.setdefault("k", v)`` calls inside each *declared* producer
  function (``.update({...})`` literals are covered because every dict
  literal in a producer body is harvested), plus the AnnAssign field
  names of declared record dataclasses (``BenchmarkRecord``,
  ``JobEvent`` — serialized with ``dataclasses.asdict``);
- **read keys** per consumer — Load-context subscripts with constant
  string slices, ``.get("k")`` / ``.pop("k")`` calls, and
  ``"k" in x`` membership tests inside each declared consumer;
- **validator mentions** — the consumer read set *plus* every string
  constant inside tuple/list/set/dict literals in the validator body
  and inside module-level constants the body references by name (so a
  ``(("trace", str), ...)`` type table or a ``SPAN_NAMES`` tuple counts
  as coverage).

Rules (stable IDs in `analysis/findings.RULES`):

- **SCHEMA-001** (error) — a key read by a declared consumer that no
  declared producer (of any family) writes and that is not on the
  family's ``historical`` allowlist: a crash or silent-None waiting for
  the next ledger.
- **SCHEMA-002** (error) — a family's validator does not mention every
  key its schema-scoped producers write: the
  ``validate_serve_record``-lags-the-producer failure mode.
- **SCHEMA-003** (warn) — a key written by some family that no declared
  consumer anywhere reads and that is not on the family's
  ``OUTPUT_ONLY`` allowlist with a reviewed reason.
- **SCHEMA-004** (error) — one key written with structurally
  incompatible value shapes (scalar vs dict vs list) across the
  producers of one family, unless the family declares the key
  polymorphic.
- **SCHEMA-005** (error) — a family with a durable writer but no
  declared `obs/history.py` ingest route and no declared NON_HISTORY
  reason: the observatory's coverage contract, made mechanical.

Conventions are declared, not inferred (the concurrency certifier's
trust-boundary model): `RECORD_FAMILIES` maps each family to its
producer roots, validator surfaces, consumers, and allowlists, and the
selftest fails on any entry naming a vanished surface. The selftest
also ties the table back to the crash-consistency layer: every module
in `faults/audit.WRITER_REGISTRY` (parsed from its AST, never
imported) must host a declared producer or record dataclass, and every
``write_raw({...literal...})`` call site must sit inside a declared
producer — so a new durable record family cannot ship schema-unchecked.

Known limits of the static approximation (also DESIGN §25): key
harvesting is flat (a nested dict's keys join the family's key set at
one level — the rules cannot distinguish ``extras["serve"]["queue"]``
from a top-level ``queue``); dynamic keys (``d[name] = v``, dict
comprehensions, ``**splat``) are invisible, which is why
``obs_snapshot``'s per-series keys ride a registry aux producer and the
round-status wrapper keys are `historical`; attribute-style dataclass
reads (``rec.tflops_per_device``) are below the read harvester's
resolution, so dataclass fields are exempt from SCHEMA-003; and
SCHEMA-001's write universe is global across families, because shared
consumer helpers (`digest_jsonl._row`) read several families in one
body. Everything here is stdlib-only: the audit must run from `lint`
on machines without a backend, in well under a second.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import sys
import tempfile
from pathlib import Path
from typing import Any, Iterable

from tpu_matmul_bench.analysis.findings import Finding

# --------------------------------------------------------------------------
# declaration model


@dataclasses.dataclass(frozen=True)
class Family:
    """One record family's declared producer/consumer contract.

    Quals are ``"<rel>::<func>"`` or ``"<rel>::<Class>.<method>"`` with
    ``<rel>`` a package-relative posix path (`scripts/` and the repo's
    `bench.py` driver are addressable too). `producers` are the
    schema-scoped writers the validator must cover; `aux_producers`
    contribute written keys (nested stats blocks owned by other
    classes) without widening the validator obligation; record
    dataclasses contribute their AnnAssign field names the same way.
    An empty `validator` skips SCHEMA-002 for the family — a statement
    that the family's schema authority is its dataclass or its
    consumers, not a checking function."""

    producers: tuple[str, ...] = ()
    aux_producers: tuple[str, ...] = ()
    record_dataclasses: tuple[str, ...] = ()
    validator: tuple[str, ...] = ()
    consumers: tuple[str, ...] = ()
    #: key -> reviewed reason: written for downstream tools, read by no
    #: in-repo consumer (SCHEMA-003 allowlist)
    output_only: dict[str, str] = dataclasses.field(default_factory=dict)
    #: key -> reviewed reason: read by consumers but written by no LIVE
    #: producer (legacy keys in committed ledgers, external wrappers) —
    #: SCHEMA-001 allowlist
    historical: dict[str, str] = dataclasses.field(default_factory=dict)
    #: keys deliberately written with more than one value shape
    polymorphic: tuple[str, ...] = ()
    durable: bool = True
    #: `obs/history.py` function that routes this family into the
    #: metric-history store (SCHEMA-005's evidence)
    ingest: str | None = None
    #: reviewed reason a durable family is NOT history-ingested
    non_history: str | None = None


# --------------------------------------------------------------------------
# the shipped declaration table — the checked record-schema model

RECORD_FAMILIES: dict[str, Family] = {
    # the BenchmarkRecord ledger line every benchmark program writes;
    # its schema authority is the dataclass, extras are per-program
    "bench_ledger": Family(
        aux_producers=(
            "utils/timing.py::sample_stats",
            "analysis/comms_model.py::wire_bytes_summary",
            "analysis/comms_model.py::hier_wire_bytes_summary",
            "analysis/memory_model.py::check_stream_budget",
            "parallel/modes.py::validate",
            "parallel/collectives.py::comm_quant_record_extra",
            "parallel/stream_k.py::stream_gate",
            "parallel/overlap.py::_vs_baseline_mode",
            "benchmarks/matmul_benchmark.py::_cost_extras",
            "benchmarks/pallas_tune.py::_candidate_cost",
        ),
        record_dataclasses=("utils/reporting.py::BenchmarkRecord",),
        consumers=(
            "scripts/digest_jsonl.py::_row",
            "scripts/digest_jsonl.py::_comm_quant_bits",
            "scripts/digest_jsonl.py::_frontier_lines",
            "scripts/digest_jsonl.py::_per_link_lines",
            "campaign/store.py::CampaignStore.summary",
            "campaign/store.py::_read_ledger",
            "obs/history.py::_bench_labels",
            "obs/history.py::_sample_noise_pct",
            "obs/history.py::_predicted_seconds",
            "obs/history.py::_predicted_comm_seconds",
            "obs/history.py::_attribution",
            "obs/history.py::_ledger_points",
        ),
        historical={
            # r2-r5 era extras still present in committed measurement
            # ledgers; the digest must keep rendering them even though
            # no live producer writes them anymore
            "grid_order": "r3 pallas sweep key in committed ledgers",
            "ksplit": "r3 pallas k-split sweep key in committed ledgers",
            "chain": "r4 fused-chain label in committed ledgers",
            "kernel": "r4 kernel label in committed ledgers",
            "confirm_pass": "r4 tie-confirmation flag in committed "
                            "ledgers",
            "tie_margin_pct": "r4 tie margin in committed ledgers",
            "superseded_by": "pallas_tune stamps it on overwritten "
                             "sweep rows at rerun time, not at write "
                             "time",
            "throughput_unit": "membw ledger unit label in committed "
                               "ledgers",
            "timing_reliable": "r2 wall-clock-confidence flag in "
                               "committed ledgers",
            "block_m": "written via the dynamic f'block_{dim}' "
                       "comprehension in parallel/overlap.py::"
                       "_explicit_blocks — below static resolution",
            "block_n": "dynamic f'block_{dim}' key (see block_m)",
            "block_k": "dynamic f'block_{dim}' key (see block_m)",
        },
        output_only={
            "payload": "per-link wire split (payload vs scale bytes) in "
                       "the analytic summary — forensic detail under "
                       "the consumed totals",
            "scale": "per-link wire split detail (see payload)",
            "block": "wire-format block size echoed into the per-link "
                     "rows so a ledger line names its quantization",
            "comm_seconds_rel": "model-vs-measured ratio kept next to "
                                "the absolute seconds the digest reads",
            "budget_bytes": "stream-budget gate evidence: the digest "
                            "renders the verdict, the operands stay "
                            "for forensics",
            "resident_bytes": "stream-budget gate evidence (see "
                              "budget_bytes)",
            "full_problem_gib": "stream-k gate evidence: why streaming "
                                "was (not) required, for humans reading "
                                "the ledger",
            "nonstreaming_over_budget": "stream-k gate evidence (see "
                                        "full_problem_gib)",
            "min_ms": "sample floor next to the consumed avg/p50/noise "
                      "stats — kept so outlier triage needs no rerun",
            "baseline": "names the serialized leg an overlap speedup "
                        "was measured against; the digest reads the "
                        "speedup",
            "baseline_time_ms": "the serialized leg's wall time (see "
                                "baseline)",
        },
        ingest="_ledger_points",
    ),
    # the serve ledger's extras["serve"] block (+ per-tenant rows,
    # per-bucket rows, and the pod block when --mesh is live)
    "serve_record": Family(
        producers=(
            "serve/service.py::serve_stats",
            "serve/service.py::_percentiles_ms",
            "serve/service.py::_tenant_rows",
            "serve/service.py::_bucket_breakdown",
            "serve/pod.py::_pod_block",
        ),
        aux_producers=(
            "serve/service.py::_serve_record",
            "serve/service.py::run_ab",
            "serve/service.py::_explore_block",
            "serve/service.py::_ab_verdict",
            "serve/pod.py::_pod_arm",
            "serve/queue.py::AdmissionQueue.stats",
            "serve/scheduler.py::ContinuousScheduler.stats",
            "serve/cache.py::ExecutableCache.stats",
            "tune/online.py::OnlineExplorer.summary",
            "tune/online.py::OnlineExplorer.decisions",
            "serve/service.py::_attach_cost_analysis",
        ),
        validator=("serve/service.py::validate_serve_record",),
        consumers=(
            "scripts/digest_jsonl.py::_serve_row",
            "scripts/digest_jsonl.py::_serve_sublines",
            "campaign/store.py::CampaignStore.summary",
            "obs/history.py::_serve_point",
            "obs/history.py::_pod_points",
            # the human renderings and cross-checks read far more of
            # the stats block than the digest tables do
            "serve/service.py::_report_summary",
            "serve/service.py::run_selftest",
            "serve/service.py::_tenant_rows",
            "serve/pod.py::_MergedCaches.stats",
            "serve/pod.py::PodQueue.stats",
            "serve/pod.py::_report_pod",
            "obs/cli.py::_selftest_findings",
        ),
        output_only={
            "window_ms": "fixed-window queue config echoed into stats "
                         "so a ledger line names its admission policy",
            "preemptions": "continuous-scheduler diagnostic counter — "
                           "tail triage evidence, no gate reads it",
            "service_est_ms": "scheduler's internal service estimate, "
                              "kept to explain its batching choices",
            "slo_sheds": "scheduler diagnostic counter (see "
                         "preemptions)",
            "starvation_ms": "starvation-promotion config echo (see "
                             "window_ms)",
            "starvation_promotions": "scheduler diagnostic counter "
                                     "(see preemptions)",
            "db": "path of the explore DB the run promoted into — "
                  "provenance for the online-tuning audit trail",
            "baseline": "A/B verdict context: the digest renders the "
                        "verdict, the arm summaries stay for forensics",
            "candidate": "A/B verdict context (see baseline)",
            "tolerance_pct": "A/B verdict context (see baseline)",
            "min_samples": "explore-gate config echo: why a bucket did "
                           "(not) promote, next to the consumed verdict",
        },
        ingest="_serve_point",
    ),
    # the train ledger's extras["train"] block (phase split, ZeRO
    # config, update-drift series, analytic wire summary)
    "train_record": Family(
        producers=("train/harness.py::bench_one",),
        aux_producers=(
            "train/harness.py::validate_step",
            "analysis/comms_model.py::train_wire_bytes_summary",
        ),
        validator=("train/harness.py::validate_train_record",),
        consumers=(
            "scripts/digest_jsonl.py::_train_row",
            "obs/history.py::_train_points",
        ),
        historical={
            "fwd_s": "phase-split key: written via the f'{phase}_s' "
                     "loop over step.PHASES in bench_one, below static "
                     "resolution",
            "bwd_s": "dynamic f'{phase}_s' key (see fwd_s)",
            "grad_comm_s": "dynamic f'{phase}_s' key (see fwd_s)",
            "update_s": "dynamic f'{phase}_s' key (see fwd_s)",
            "allgather_s": "dynamic f'{phase}_s' key (see fwd_s)",
        },
        output_only={
            "validation_tolerance": "verdict context: the digest "
                                    "renders 'validation'; the "
                                    "tolerance keeps a FAILED line "
                                    "self-explanatory",
            "comm_seconds_rel": "model-vs-measured ratio kept next to "
                                "the absolute seconds (bench_ledger "
                                "has the same column)",
        },
        ingest="_train_points",
    ),
    # streamed per-batch progress lines on the serve ledger
    "serve_batch": Family(
        producers=("serve/service.py::_worker_drain",),
        validator=("serve/service.py::validate_serve_batch_record",),
        consumers=(
            "scripts/digest_jsonl.py::main",
            "faults/audit.py::_validate_serve_line",
        ),
        output_only={
            "batch_ms": "per-batch wall time for humans tailing the "
                        "live ledger; the audit only checks the line's "
                        "shape and ordering",
        },
        non_history="liveness evidence for the crash-consistency "
                    "audit, not a measurement; the headline serve "
                    "record carries the gated numbers",
    ),
    # per-request terminal span records from the flight recorder
    "serve_span": Family(
        producers=(
            "serve/trace.py::FlightRecorder.terminal",
            "serve/trace.py::request_spans",
            "serve/trace.py::failure_spans",
        ),
        aux_producers=("serve/trace.py::tail_attribution",),
        validator=("serve/trace.py::validate_serve_span_record",),
        consumers=(
            "serve/trace.py::read_trace_records",
            "serve/trace.py::tail_attribution",
            "serve/trace.py::render_explain",
            "serve/trace.py::run_explain",
            "scripts/digest_jsonl.py::_digest_serve_spans",
            "scripts/digest_jsonl.py::_tail_shares",
            "obs/history.py::_serve_tail_points",
        ),
        historical={
            "compile": "tail-component label: the shares block's keys "
                       "come from TAIL_COMPONENTS via a dict "
                       "comprehension, below static resolution",
            "queue_wait": "tail-component label (see compile)",
            "batch_wait": "tail-component label (see compile)",
            "execute": "tail-component label (see compile)",
        },
        output_only={
            "quantile": "tail-attribution provenance: which quantile "
                        "the threshold was computed at — explain-output "
                        "readers need it, no code path does",
            "wall_ms_sum": "tail-attribution denominator kept so the "
                           "shares block is auditable by hand",
        },
        ingest="_serve_tail_points",
    ),
    # the campaign resume journal (fsynced JobEvent lines)
    "campaign_journal": Family(
        record_dataclasses=("campaign/state.py::JobEvent",),
        consumers=(
            "campaign/state.py::load_events",
            "scripts/digest_jsonl.py::_campaign_status_counts",
        ),
        non_history="execution state (status transitions), not a "
                    "measurement; journal.jsonl is in history's "
                    "_NON_MEASUREMENT_NAMES",
    ),
    # tuning-DB cells (measurements/tune_db.jsonl)
    "tune_cell": Family(
        producers=("tune/db.py::Cell.to_record",),
        validator=(
            "tune/db.py::Cell.from_record",
            "tune/db.py::TuningDB.validate",
        ),
        consumers=(
            "tune/db.py::Cell.from_record",
            "scripts/digest_jsonl.py::_digest_tune",
        ),
        non_history="cells cite measurement artifacts; history tracks "
                    "the measurements themselves (tune_db.jsonl is in "
                    "_NON_MEASUREMENT_NAMES)",
    ),
    # serialized-executable store manifest lines
    "exec_artifact": Family(
        producers=("tune/artifacts.py::ArtifactStore.put",),
        validator=("tune/artifacts.py::ArtifactStore.validate",),
        consumers=(
            "tune/artifacts.py::ArtifactStore.load",
            "tune/artifacts.py::ArtifactStore.lookup",
            "tune/artifacts.py::ArtifactStore.get_blob",
            "tune/artifacts.py::ArtifactStore.records",
            "tune/artifacts.py::ArtifactStore.stale_reasons",
            "scripts/digest_jsonl.py::_digest_artifacts",
        ),
        non_history="serialized-executable provenance, not a "
                    "measurement; integrity is ART-001/002's contract",
    ),
    # obs metrics snapshots (obs_snapshot.jsonl)
    "obs_snapshot": Family(
        producers=("obs/export.py::snapshot_record",),
        aux_producers=(
            "obs/registry.py::MetricsRegistry.snapshot",
            "obs/registry.py::_histogram_summary",
        ),
        consumers=(
            "obs/export.py::read_snapshots",
            "obs/export.py::prometheus_text",
            "scripts/digest_jsonl.py::_digest_obs",
        ),
        historical={
            "p50": "histogram quantile label: written via the "
                   "QUANTILES loop variable in _histogram_summary, "
                   "below static resolution",
            "p95": "quantile label (see p50)",
            "p99": "quantile label (see p50)",
        },
        non_history="live gauges for `obs status`, not retained "
                    "measurements; obs_snapshot.jsonl is in "
                    "_NON_MEASUREMENT_NAMES",
    ),
    # the metric-history store's point records (history.jsonl)
    "history_point": Family(
        producers=("obs/history.py::_make_point",),
        aux_producers=(
            "obs/history.py::_round_points",
            "obs/history.py::_bench_labels",
            "obs/history.py::_serve_point",
            "obs/history.py::_pod_points",
            "obs/history.py::_train_points",
            "obs/history.py::_ledger_points",
            "obs/history.py::_serve_tail_points",
            "obs/history.py::_attribution",
            "obs/history.py::_predicted_seconds",
            "obs/history.py::_predicted_comm_seconds",
            "obs/history.py::HistoryStore.append",
        ),
        validator=("obs/history.py::HistoryStore.validate",),
        consumers=(
            "obs/history.py::HistoryStore.series",
            "obs/history.py::HistoryStore.identities",
            "obs/history.py::HistoryStore.max_seq",
            "obs/history.py::_headline_point",
            "obs/history.py::baseline_rows_for_campaign",
            "obs/detect.py::_series_label",
            "obs/detect.py::_best_per_round",
            "obs/detect.py::_series_findings",
            "obs/detect.py::_residual_findings",
            "obs/detect.py::detect_findings",
            "obs/report.py::_trajectory",
            "obs/report.py::_group_rows",
            "obs/report.py::render",
            "obs/report.py::_residual_section",
            "obs/report.py::_verdict_section",
            "scripts/digest_jsonl.py::_digest_history",
        ),
        historical={
            "bench": "report group label: a value of the point's "
                     "'kind' field used as a local grouping key in "
                     "obs/report.py::render, not a record key",
            "tune": "report group label (see bench)",
            "serve": "report group label (see bench)",
            "serve_tail": "report group label (see bench)",
            "train": "report group label (see bench)",
            "fault_audit": "report group label (see bench)",
        },
        output_only={
            "measured": "residual drill-down: residual_pct is the "
                        "consumed signal; the measured/predicted split "
                        "stays for forensic attribution",
            "predicted": "residual drill-down (see measured)",
            "total_s": "sub-key of the measured block (see measured)",
            "link_formats": "series-identity label: consumed via the "
                            "labels fingerprint, never read by name",
            "implausible_above_peak_tflops": "detail flag explaining "
                                             "why a point was demoted "
                                             "to unavailable — triage "
                                             "evidence for humans",
        },
        ingest="ingest",
    ),
    # fault-audit cell verdicts (the chaos certifier's ledger)
    "fault_audit": Family(
        producers=(
            "faults/audit.py::run_cell",
            "faults/audit.py::run_audit",
        ),
        consumers=(
            "scripts/digest_jsonl.py::_digest_fault_audit",
            "obs/history.py::_ledger_points",
        ),
        output_only={
            "fault": "the injected FaultSpec in inline form — the "
                     "replay recipe for a failed cell; verdict "
                     "consumers key on cell/subsystem",
        },
        ingest="_ledger_points",
    ),
    # schema-v2 manifest lines (every ledger's first record)
    "manifest": Family(
        producers=(
            "utils/telemetry.py::build_manifest",
            "serve/service.py::_config_manifest",
        ),
        aux_producers=(
            "analysis/findings.py::write_ledger",
            "analysis/cli.py::main",
            "obs/context.py::trace_block",
        ),
        consumers=(
            "utils/telemetry.py::is_manifest",
            "scripts/digest_jsonl.py::main",
            "scripts/digest_jsonl.py::_digest_lint",
            "campaign/store.py::CampaignStore.merged_records",
            "serve/trace.py::run_explain",
            "obs/history.py::_serve_point",
        ),
        output_only={
            # the manifest IS the forensic record: most of its columns
            # exist so two runs can be diffed by hand, not so code can
            # read them back
            "fail_on": "lint-run provenance: the gate the ledger was "
                       "written under",
            "specs": "lint-run provenance: which audit groups ran",
            "pid": "trace-block provenance for correlating a ledger "
                   "with its process logs",
            "concurrency": "serve-run repro knob, diffed by humans",
            "duration_s": "serve-run repro knob (see concurrency)",
            "explore_db": "serve-run repro knob (see concurrency)",
            "prewarm": "serve-run repro knob (see concurrency)",
            "starvation_ms": "serve-run repro knob (see concurrency)",
            "window_ms": "serve-run repro knob (see concurrency)",
            "jaxlib_version": "environment provenance, diffed by "
                              "humans chasing a regression",
            "process_count": "environment provenance (see "
                             "jaxlib_version)",
            "precision": "run-config provenance (see jaxlib_version)",
            "seed": "run-config provenance (see jaxlib_version)",
            "warmup": "run-config provenance (see jaxlib_version)",
        },
        non_history="provenance, not measurement; manifests ride the "
                    "measurement ledgers and are read as labels "
                    "(serve_config) by the ingest dispatchers",
    ),
    # lint findings ledger lines (`lint --json-out`)
    "lint_finding": Family(
        producers=("analysis/findings.py::Finding.to_record",),
        aux_producers=(
            "analysis/findings.py::write_ledger",
            "analysis/findings.py::summarize",
        ),
        consumers=("scripts/digest_jsonl.py::_digest_lint",),
        output_only={
            "details": "structured evidence payload a human (or a "
                       "future tool) drills into; the digest renders "
                       "rule/severity/where/message",
            "rule_doc": "the rule's one-line contract inlined so a "
                        "ledger is readable without the source tree",
        },
        non_history="lint verdicts gate merges directly; the history "
                    "store tracks measured performance, not static "
                    "findings",
    ),
    # the parent round driver's health line on stdout (bench.py)
    "round_status": Family(
        producers=(
            "bench.py::_emit",
            "bench.py::_last_known_good",
        ),
        consumers=("obs/history.py::_round_points",),
        historical={
            "parsed": "BENCH_rNN.json wrapper written by the external "
                      "round driver around bench.py's stdout line",
            "rc": "external round-driver wrapper key",
            "ok": "external MULTICHIP_rNN.json wrapper key",
            "skipped": "external MULTICHIP_rNN.json wrapper key",
            "n_devices": "external MULTICHIP_rNN.json wrapper key",
        },
        output_only={
            "last_rc": "retry breadcrumb on the health line for humans "
                       "tailing the round driver; _round_points reads "
                       "the wrapper's rc, not this echo",
        },
        ingest="_round_points",
    ),
}

#: `write_raw({...literal...})` call sites that are NOT record
#: producers — qual -> reviewed reason (the selftest's write-site
#: coverage leg; anything else must be a declared producer)
WRITE_SITE_ALLOWLIST: dict[str, str] = {}

#: WRITER_REGISTRY modules exempt from the family tie-in: they host the
#: durable-write *mechanism*, not a record schema
_REGISTRY_EXEMPT = frozenset({"utils/durable.py"})

# --------------------------------------------------------------------------
# tree model


@dataclasses.dataclass
class _Tree:
    #: qual -> function AST node (nested defs are also indexed under
    #: their own name, concurrency-certifier style)
    funcs: dict[str, ast.AST]
    #: "rel::Class" -> AnnAssign field names, in declaration order
    classes: dict[str, list[str]]
    #: rel -> module-level constant name -> string constants under it
    str_consts: dict[str, dict[str, tuple[str, ...]]]
    #: (enclosing qual, lineno) of write_raw(<dict literal>) calls
    write_raw_sites: list[tuple[str, int]]
    #: module rels listed in faults/audit.WRITER_REGISTRY (AST-parsed)
    writer_registry: tuple[str, ...]


def _package_root() -> Path:
    return Path(__file__).resolve().parents[1]


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def _scan_files(root: Path | None) -> list[tuple[str, Path]]:
    """(rel, path) pairs in scope. Real tree: the whole package plus
    the repo's scripts/ directory and the bench.py round driver, so
    every producer and consumer surface is addressable. Fixture trees
    are scanned whole, relative to their root."""
    if root is not None:
        return sorted((p.relative_to(root).as_posix(), p)
                      for p in root.rglob("*.py"))
    pkg = _package_root()
    repo = _repo_root()
    files = [(p.relative_to(pkg).as_posix(), p) for p in pkg.rglob("*.py")]
    scripts = repo / "scripts"
    if scripts.is_dir():
        files.extend((f"scripts/{p.name}", p) for p in scripts.glob("*.py"))
    driver = repo / "bench.py"
    if driver.is_file():
        files.append(("bench.py", driver))
    return sorted(files)


def _module_str_consts(mod: ast.Module) -> dict[str, tuple[str, ...]]:
    """Module-level `NAME = <literal>` whose literal contains string
    constants — the SPAN_NAMES / TERMINAL_STATES shape a validator
    references by name."""
    out: dict[str, tuple[str, ...]] = {}
    for node in mod.body:
        targets: list[ast.expr] = []
        value: ast.expr | None = None
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets, value = [node.target], node.value
        if value is None or not isinstance(
                value, (ast.Tuple, ast.List, ast.Set, ast.Dict, ast.Call)):
            continue
        strs = tuple(sorted({n.value for n in ast.walk(value)
                             if isinstance(n, ast.Constant)
                             and isinstance(n.value, str)}))
        if not strs:
            continue
        for tgt in targets:
            if isinstance(tgt, ast.Name):
                out[tgt.id] = strs
    return out


def _registry_rels(mod: ast.Module) -> tuple[str, ...]:
    """Keys of the module-level WRITER_REGISTRY dict literal."""
    for node in mod.body:
        value = None
        if isinstance(node, ast.Assign):
            if any(isinstance(t, ast.Name) and t.id == "WRITER_REGISTRY"
                   for t in node.targets):
                value = node.value
        elif isinstance(node, ast.AnnAssign):
            if isinstance(node.target, ast.Name) \
                    and node.target.id == "WRITER_REGISTRY":
                value = node.value
        if isinstance(value, ast.Dict):
            return tuple(sorted(
                k.value for k in value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)))
    return ()


def _index_tree(root: Path | None) -> _Tree:
    funcs: dict[str, ast.AST] = {}
    classes: dict[str, list[str]] = {}
    str_consts: dict[str, dict[str, tuple[str, ...]]] = {}
    write_sites: list[tuple[str, int]] = []
    registry: tuple[str, ...] = ()

    def walk_body(body: Iterable[ast.stmt], rel: str,
                  cls: str | None) -> None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (f"{rel}::{cls}.{node.name}" if cls
                        else f"{rel}::{node.name}")
                funcs[qual] = node
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "write_raw"
                            and sub.args
                            and isinstance(sub.args[0], ast.Dict)):
                        write_sites.append((qual, sub.lineno))
                walk_body(node.body, rel, cls)  # nested defs
            elif isinstance(node, ast.ClassDef):
                fields = [s.target.id for s in node.body
                          if isinstance(s, ast.AnnAssign)
                          and isinstance(s.target, ast.Name)]
                classes[f"{rel}::{node.name}"] = fields
                walk_body(node.body, rel, node.name)

    for rel, path in _scan_files(root):
        try:
            mod = ast.parse(path.read_text(errors="replace"))
        except (OSError, SyntaxError):
            continue
        str_consts[rel] = _module_str_consts(mod)
        if rel == "faults/audit.py":
            registry = _registry_rels(mod)
        walk_body(mod.body, rel, None)

    return _Tree(funcs, classes, str_consts, sorted(write_sites), registry)


# --------------------------------------------------------------------------
# per-function harvesters

#: call names whose result is structurally a scalar
_SCALAR_CALLS = frozenset({
    "round", "int", "float", "str", "bool", "len", "min", "max", "sum",
    "abs",
})

#: call names whose result is structurally a dict / a list
_DICT_CALLS = frozenset({"dict"})
_LIST_CALLS = frozenset({"list", "sorted", "tuple", "set"})


def _shape_of(node: ast.expr | None) -> str:
    """Coarse structural class of a written value: 'dict', 'list',
    'scalar', or 'unknown' (never conflicts). Conditionals, names, and
    attribute chains are unknown on purpose — SCHEMA-004 only fires on
    *provable* shape splits."""
    if node is None:
        return "unknown"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(node, (ast.List, ast.ListComp, ast.Tuple, ast.Set,
                         ast.SetComp, ast.GeneratorExp)):
        return "list"
    if isinstance(node, ast.Constant):
        return "unknown" if node.value is None else "scalar"
    if isinstance(node, ast.UnaryOp):
        return _shape_of(node.operand)
    if isinstance(node, (ast.JoinedStr, ast.Compare, ast.BoolOp)):
        return "scalar"
    if isinstance(node, ast.Call):
        name = ""
        if isinstance(node.func, ast.Name):
            name = node.func.id
        elif isinstance(node.func, ast.Attribute):
            name = node.func.attr
        if name in _SCALAR_CALLS:
            return "scalar"
        if name in _DICT_CALLS:
            return "dict"
        if name in _LIST_CALLS:
            return "list"
    return "unknown"


def _harvest_writes(fn: ast.AST,
                    rel: str) -> dict[str, dict[str, tuple[str, int]]]:
    """key -> {shape: first (rel, lineno) witness} for one producer."""
    out: dict[str, dict[str, tuple[str, int]]] = {}

    def add(key: str, shape: str, lineno: int) -> None:
        out.setdefault(key, {}).setdefault(shape, (rel, lineno))

    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    add(k.value, _shape_of(v), node.lineno)
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.slice, ast.Constant) \
                        and isinstance(tgt.slice.value, str):
                    add(tgt.slice.value, _shape_of(getattr(node, "value",
                                                           None)),
                        node.lineno)
        elif isinstance(node, ast.Call):
            func = node.func
            if isinstance(func, ast.Name) and func.id == "dict":
                for kw in node.keywords:
                    if kw.arg is not None:
                        add(kw.arg, _shape_of(kw.value), node.lineno)
            elif isinstance(func, ast.Attribute) \
                    and func.attr == "setdefault" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                val = node.args[1] if len(node.args) > 1 else None
                add(node.args[0].value, _shape_of(val), node.lineno)
    return out


def _loop_key_vars(fn: ast.AST) -> dict[str, tuple[str, ...]]:
    """`for key in ("a", "b"):` loop variables -> their constant key
    sets. Function-scoped and name-keyed (no control-flow analysis): a
    reused loop-variable name unions its key sets, which for a read
    harvest only ever adds witnesses."""
    out: dict[str, tuple[str, ...]] = {}
    for node in ast.walk(fn):
        if not isinstance(node, (ast.For, ast.comprehension)):
            continue
        if not isinstance(node.target, ast.Name):
            continue
        it = node.iter
        if not isinstance(it, (ast.Tuple, ast.List, ast.Set)):
            continue
        keys = tuple(e.value for e in it.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str))
        if keys and len(keys) == len(it.elts):
            out[node.target.id] = out.get(node.target.id, ()) + keys
    return out


def _harvest_reads(fn: ast.AST, rel: str) -> dict[str, tuple[str, int]]:
    """key -> first (rel, lineno) witness of a consumer read."""
    out: dict[str, tuple[str, int]] = {}
    loop_keys = _loop_key_vars(fn)

    def add(key: str, lineno: int) -> None:
        out.setdefault(key, (rel, lineno))

    def add_expr(expr: ast.AST, lineno: int) -> None:
        """A key expression: a string constant, or a loop variable
        ranging over string constants (`for k in ("a", "b"): d[k]`)."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            add(expr.value, lineno)
        elif isinstance(expr, ast.Name) and expr.id in loop_keys:
            for key in loop_keys[expr.id]:
                add(key, lineno)

    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, ast.Load):
            add_expr(node.slice, node.lineno)
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("get", "pop") and node.args:
            add_expr(node.args[0], node.lineno)
        elif isinstance(node, ast.Compare) \
                and any(isinstance(op, (ast.In, ast.NotIn))
                        for op in node.ops):
            left = node.left
            if isinstance(left, ast.Constant) \
                    and isinstance(left.value, str):
                # identifier-shaped only: `"{" in series` is substring
                # search, not a key probe
                if left.value.isidentifier():
                    add(left.value, node.lineno)
            elif isinstance(left, ast.Name) and left.id in loop_keys:
                for key in loop_keys[left.id]:
                    add(key, node.lineno)
    return out


def _harvest_mentions(fn: ast.AST, rel: str, tree: _Tree) -> set[str]:
    """The validator coverage set: strict reads plus every string
    constant in tuple/list/set/dict literals in the body, plus the
    string contents of module-level constants the body names."""
    mentions = set(_harvest_reads(fn, rel))
    consts = tree.str_consts.get(rel, {})
    for node in ast.walk(fn):
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            mentions.update(n.value for n in ast.walk(node)
                            if isinstance(n, ast.Constant)
                            and isinstance(n.value, str))
        elif isinstance(node, ast.Dict):
            mentions.update(k.value for k in node.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str))
        elif isinstance(node, ast.Name) and node.id in consts:
            mentions.update(consts[node.id])
    return mentions


# --------------------------------------------------------------------------
# the rules


def _family_writes(tree: _Tree, fam: Family, *, validated_only: bool,
                   ) -> dict[str, dict[str, tuple[str, int]]]:
    """Merged key -> {shape: witness} over the family's producers
    (plus aux producers and dataclass fields unless validated_only)."""
    quals = fam.producers if validated_only \
        else fam.producers + fam.aux_producers
    merged: dict[str, dict[str, tuple[str, int]]] = {}
    for qual in quals:
        fn = tree.funcs.get(qual)
        if fn is None:
            continue  # the selftest's staleness leg reports it
        rel = qual.partition("::")[0]
        for key, shapes in _harvest_writes(fn, rel).items():
            slot = merged.setdefault(key, {})
            for shape, wit in shapes.items():
                slot.setdefault(shape, wit)
    if not validated_only:
        for cqual in fam.record_dataclasses:
            rel = cqual.partition("::")[0]
            for field in tree.classes.get(cqual, []):
                merged.setdefault(field, {}).setdefault("unknown", (rel, 0))
    return merged


def _family_reads(tree: _Tree, fam: Family, *, contract: bool,
                  ) -> dict[str, list[tuple[str, int]]]:
    """key -> read witnesses across the family's declared consumers.

    With contract=True, keys a consumer's own body also *writes* are
    dropped for that consumer: a function that builds a dict literal
    and reads it back (a severity-totals table, a per-state counter)
    is locally satisfied, not a record-contract read. The raw
    (contract=False) set is what SCHEMA-003 wants — any read anywhere
    proves a written key is load-bearing."""
    merged: dict[str, list[tuple[str, int]]] = {}
    for qual in fam.consumers:
        fn = tree.funcs.get(qual)
        if fn is None:
            continue
        rel = qual.partition("::")[0]
        self_written = set(_harvest_writes(fn, rel)) if contract else set()
        for key, wit in _harvest_reads(fn, rel).items():
            if key in self_written:
                continue
            merged.setdefault(key, []).append(wit)
    return merged


def schema_findings(
    root: str | Path | None = None, *,
    families: dict[str, Family] | None = None,
) -> list[Finding]:
    """SCHEMA-001..005 over the tree (the whole package plus scripts/
    and bench.py by default; tests inject fixture trees plus their own
    family tables). Deterministic: findings sort by (rule, where,
    message), so two runs on one tree are byte-identical."""
    base = Path(root) if root is not None else None
    fams = RECORD_FAMILIES if families is None else families
    tree = _index_tree(base)

    writes = {name: _family_writes(tree, fam, validated_only=False)
              for name, fam in fams.items()}
    reads = {name: _family_reads(tree, fam, contract=True)
             for name, fam in fams.items()}
    global_written: set[str] = set()
    for keyed in writes.values():
        global_written.update(keyed)
    # SCHEMA-003's read universe: every raw consumer read plus every
    # validator read — a validator probing a key (reconciliation
    # checks) proves the key is load-bearing
    global_read: set[str] = set()
    for name, fam in fams.items():
        global_read.update(_family_reads(tree, fam, contract=False))
        for vqual in fam.validator:
            fn = tree.funcs.get(vqual)
            if fn is not None:
                global_read.update(
                    _harvest_reads(fn, vqual.partition("::")[0]))

    findings: list[Finding] = []
    for name in sorted(fams):
        fam = fams[name]

        # SCHEMA-001: consumer reads nothing writes
        for key in sorted(reads[name]):
            if key in global_written or key in fam.historical:
                continue
            wit = sorted(reads[name][key])[0]
            findings.append(Finding(
                "SCHEMA-001", f"{wit[0]}:{wit[1]}",
                f"family {name!r}: consumer reads key {key!r} that no "
                "declared producer writes — a KeyError or silent None "
                "waiting for the next ledger (write it, or declare it "
                "in the family's `historical` allowlist with a reason)",
                details={"family": name, "key": key,
                         "readers": sorted(
                             f"{r}:{ln}" for r, ln in reads[name][key])}))

        # SCHEMA-002: validator lags the schema-scoped producers
        if fam.validator:
            mentioned: set[str] = set()
            vrel = fam.validator[0].partition("::")[0]
            for vqual in fam.validator:
                fn = tree.funcs.get(vqual)
                if fn is not None:
                    mentioned |= _harvest_mentions(
                        fn, vqual.partition("::")[0], tree)
            scoped = _family_writes(tree, fam, validated_only=True)
            missing = sorted(set(scoped) - mentioned)
            if missing:
                findings.append(Finding(
                    "SCHEMA-002", vrel,
                    f"family {name!r}: validator "
                    f"{' + '.join(fam.validator)} does not cover "
                    f"statically-written key(s) {missing} — the "
                    "validator lags the producer",
                    details={"family": name, "missing": missing,
                             "validator": list(fam.validator)}))

        # SCHEMA-003: written, read nowhere, not declared output-only
        for key in sorted(writes[name]):
            if key in global_read or key in fam.output_only:
                continue
            shapes = writes[name][key]
            if set(shapes) == {"unknown"} \
                    and all(ln == 0 for _, ln in shapes.values()):
                continue  # dataclass field: attribute reads are invisible
            wit = sorted(writes[name][key].values())[0]
            findings.append(Finding(
                "SCHEMA-003", f"{wit[0]}:{wit[1]}",
                f"family {name!r}: key {key!r} is written but read by "
                "no declared consumer — dead weight in every ledger "
                "line (drop it, or declare it OUTPUT_ONLY with a "
                "reviewed reason)",
                details={"family": name, "key": key}))

        # SCHEMA-004: incompatible shapes across one family's producers
        for key in sorted(writes[name]):
            shapes = {s: w for s, w in writes[name][key].items()
                      if s != "unknown"}
            if len(shapes) > 1 and key not in fam.polymorphic:
                wits = sorted(f"{r}:{ln} ({s})"
                              for s, (r, ln) in shapes.items())
                wit = sorted(shapes.values())[0]
                findings.append(Finding(
                    "SCHEMA-004", f"{wit[0]}:{wit[1]}",
                    f"family {name!r}: key {key!r} is written with "
                    f"structurally incompatible shapes "
                    f"{sorted(shapes)} across producers — consumers "
                    f"cannot branch on luck ({', '.join(wits)})",
                    details={"family": name, "key": key,
                             "shapes": sorted(shapes),
                             "witnesses": wits}))

        # SCHEMA-005: durable family with no history route and no
        # declared reason
        if fam.durable and fam.ingest is None and fam.non_history is None:
            where = (fam.producers + fam.aux_producers
                     + fam.record_dataclasses + (name,))[0]
            findings.append(Finding(
                "SCHEMA-005", where.partition("::")[0],
                f"family {name!r} has a durable writer but no declared "
                "obs/history.py ingest route and no NON_HISTORY reason "
                "— the observatory's coverage contract requires one or "
                "the other",
                details={"family": name}))

    return sorted(findings, key=lambda f: (f.rule, f.where, f.message))


# --------------------------------------------------------------------------
# declaration hygiene (the selftest's staleness leg)


def declaration_problems(
        families: dict[str, Family] | None = None,
        tree: _Tree | None = None) -> list[str]:
    """Stale-table problems on the real tree: quals naming vanished
    surfaces, dead ingest routes, WRITER_REGISTRY modules with no
    declared family, and write_raw dict-literal sites outside every
    declared producer. Empty list = the table is live."""
    fams = RECORD_FAMILIES if families is None else families
    if tree is None:
        tree = _index_tree(None)
    problems: list[str] = []

    declared_producers: set[str] = set(WRITE_SITE_ALLOWLIST)
    producer_rels: set[str] = set()
    for name in sorted(fams):
        fam = fams[name]
        for qual in (fam.producers + fam.aux_producers + fam.validator
                     + fam.consumers):
            if qual not in tree.funcs:
                problems.append(
                    f"family {name!r}: declared surface {qual} does not "
                    "exist")
        for cqual in fam.record_dataclasses:
            if cqual not in tree.classes:
                problems.append(
                    f"family {name!r}: declared record dataclass "
                    f"{cqual} does not exist")
            elif not tree.classes[cqual]:
                problems.append(
                    f"family {name!r}: record dataclass {cqual} has no "
                    "annotated fields to harvest")
        declared_producers.update(fam.producers + fam.aux_producers)
        producer_rels.update(
            q.partition("::")[0]
            for q in fam.producers + fam.aux_producers
            + fam.record_dataclasses)
        if fam.ingest is not None:
            iqual = f"obs/history.py::{fam.ingest}"
            mqual = f"obs/history.py::HistoryStore.{fam.ingest}"
            if iqual not in tree.funcs and mqual not in tree.funcs:
                problems.append(
                    f"family {name!r}: ingest route {fam.ingest!r} is "
                    "not a function in obs/history.py")

    if not tree.writer_registry:
        problems.append("faults/audit.WRITER_REGISTRY not found — the "
                        "durable-writer seed list is gone")
    for rel in tree.writer_registry:
        if rel in _REGISTRY_EXEMPT:
            continue
        if rel not in producer_rels:
            problems.append(
                f"WRITER_REGISTRY module {rel} hosts a durable writer "
                "but no RECORD_FAMILIES entry declares a producer or "
                "record dataclass in it")

    for qual, lineno in tree.write_raw_sites:
        if qual not in declared_producers:
            problems.append(
                f"write_raw dict-literal call at {qual}:{lineno} is not "
                "inside a declared producer (add the enclosing function "
                "to a family, or to WRITE_SITE_ALLOWLIST with a reason)")
    return problems


# --------------------------------------------------------------------------
# selftest (lint_ci.sh layer 15)

#: (rule, {filename: source}, broken family table, repaired table) —
#: each fixture trips exactly its rule; its repaired twin scans clean.
_SELFTEST_FIXTURES: tuple[
        tuple[str, dict[str, str], dict[str, Family],
              dict[str, Family]], ...] = (
    ("SCHEMA-001",
     {"producer.py": "def make():\n    return {'alpha': 1.0}\n",
      "consumer.py": "def read(rec):\n    return rec['beta']\n"},
     {"demo": Family(producers=("producer.py::make",),
                     consumers=("consumer.py::read",),
                     output_only={"alpha": "fixture: written for the "
                                           "repaired twin"},
                     durable=False)},
     {"demo": Family(producers=("producer.py::make",),
                     consumers=("consumer.py::read_ok",),
                     durable=False)}),
    ("SCHEMA-002",
     {"producer.py": "def make():\n"
                     "    return {'alpha': 1.0, 'beta': 2.0}\n",
      "consumer.py": "def read(rec):\n"
                     "    return rec['alpha'], rec['beta']\n",
      "check.py": "def validate(rec):\n"
                  "    return [k for k in ('alpha',) if k not in rec]\n"
                  "def validate_full(rec):\n"
                  "    return [k for k in ('alpha', 'beta')\n"
                  "            if k not in rec]\n"},
     {"demo": Family(producers=("producer.py::make",),
                     validator=("check.py::validate",),
                     consumers=("consumer.py::read",),
                     durable=False)},
     {"demo": Family(producers=("producer.py::make",),
                     validator=("check.py::validate_full",),
                     consumers=("consumer.py::read",),
                     durable=False)}),
    ("SCHEMA-003",
     {"producer.py": "def make():\n"
                     "    return {'alpha': 1.0, 'beta': 2.0}\n",
      "consumer.py": "def read(rec):\n    return rec['alpha']\n"},
     {"demo": Family(producers=("producer.py::make",),
                     consumers=("consumer.py::read",),
                     durable=False)},
     {"demo": Family(producers=("producer.py::make",),
                     consumers=("consumer.py::read",),
                     output_only={"beta": "debug counter for offline "
                                          "tooling"},
                     durable=False)}),
    ("SCHEMA-004",
     {"producer.py": "def make():\n"
                     "    return {'alpha': 1.0}\n"
                     "def make_nested():\n"
                     "    return {'alpha': {'x': 1.0}}\n",
      "consumer.py": "def read(rec):\n"
                     "    return rec['alpha'], rec['alpha']['x']\n"},
     {"demo": Family(producers=("producer.py::make",
                                "producer.py::make_nested"),
                     consumers=("consumer.py::read",),
                     durable=False)},
     {"demo": Family(producers=("producer.py::make",
                                "producer.py::make_nested"),
                     consumers=("consumer.py::read",),
                     polymorphic=("alpha",),
                     durable=False)}),
    ("SCHEMA-005",
     {"producer.py": "def make():\n    return {'alpha': 1.0}\n",
      "consumer.py": "def read(rec):\n    return rec['alpha']\n"},
     {"demo": Family(producers=("producer.py::make",),
                     consumers=("consumer.py::read",),
                     durable=True)},
     {"demo": Family(producers=("producer.py::make",),
                     consumers=("consumer.py::read",),
                     durable=True,
                     non_history="fixture stream: liveness only")}),
)

# SCHEMA-001's repaired twin reads a key that exists; give it a body
_FIXTURE_EXTRA = {
    "SCHEMA-001": {"consumer.py": "def read_ok(rec):\n"
                                  "    return rec['alpha']\n"},
}


def run_schema_selftest() -> list[Any]:
    """`lint schema selftest`: (1) the real tree must certify clean
    (warns included — OUTPUT_ONLY entries are reviewed declarations,
    not suppressions), (2) each seeded SCHEMA-001..005 fixture must
    trip exactly its rule with its registered severity and its repaired
    twin must scan clean, (3) two consecutive real-tree passes must
    render byte-identical findings, and (4) the RECORD_FAMILIES table
    must not have rotted (every declared surface lives, every
    WRITER_REGISTRY module is covered, every write_raw dict-literal
    site is a declared producer). Exits nonzero on any violation."""
    from tpu_matmul_bench.analysis.findings import RULES

    # utils.reporting imports jax at module top; this selftest is CI's
    # jax-free layer, so it prints its header block directly
    bar = "=" * 60
    print("\n".join([
        bar, "Schema-flow lint selftest", bar, "Configuration:",
        "  - Scope: package + scripts/ + bench.py",
        "  - Rules: SCHEMA-001..005",
        f"  - Record families: {len(RECORD_FAMILIES)}", bar,
    ]))

    problems: list[str] = []

    tree_findings = schema_findings()
    problems.extend(
        f"real tree: {f.rule} at {f.where}: {f.message}"
        for f in tree_findings)

    second = schema_findings()
    if json.dumps([f.to_record() for f in second]) != \
            json.dumps([f.to_record() for f in tree_findings]):
        problems.append("nondeterministic findings: two consecutive "
                        "passes over one tree differ")

    with tempfile.TemporaryDirectory(prefix="schema-seeded-") as td:
        for rule, sources, broken, repaired in _SELFTEST_FIXTURES:
            fdir = Path(td) / rule.lower()
            fdir.mkdir()
            merged = dict(sources)
            for fname, extra in _FIXTURE_EXTRA.get(rule, {}).items():
                merged[fname] = merged.get(fname, "") + extra
            for fname, src in merged.items():
                (fdir / fname).write_text(src)
            got = schema_findings(fdir, families=broken)
            fired = {f.rule for f in got}
            if rule not in fired:
                problems.append(
                    f"seeded {rule} fixture did not fire "
                    f"(got {sorted(fired)})")
            for f in got:
                if f.rule == rule and f.severity != RULES[rule][0]:
                    problems.append(
                        f"seeded {rule} fired at severity "
                        f"{f.severity!r}, registered {RULES[rule][0]!r}")
            clean = schema_findings(fdir, families=repaired)
            if clean:
                problems.append(
                    f"repaired {rule} twin is not clean: "
                    f"{[(f.rule, f.message) for f in clean][:2]}")

    problems.extend(f"stale table: {p}" for p in declaration_problems())

    if problems:
        for p in problems:
            print(f"schema selftest FAILED: {p}", file=sys.stderr)
        raise SystemExit(1)
    print(f"schema selftest ok: {len(RECORD_FAMILIES)} record families "
          f"certify clean, {len(_SELFTEST_FIXTURES)} seeded rules fire "
          "with registered severities (repaired twins clean), findings "
          "deterministic, declaration table live")
    return [f.to_record() for f in tree_findings]
