"""Findings model for the bench linter: stable rule IDs, severities, ledger.

Rule IDs are part of the tool's contract — tests and CI grep for them, so
they never change meaning or get reused. New rules append new IDs.

The findings ledger reuses the schema-v2 JSONL convention from
`utils/telemetry` / `utils/reporting`: first line a manifest record
(`record_type: "manifest"`), then one `record_type: "lint_finding"` line
per finding, then a `record_type: "lint_summary"` trailer with counts —
so existing ledger tooling (digest_jsonl, campaign stores) can ingest it
without a second parser.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

Severity = str  # "info" | "warn" | "error"

SEVERITIES = ("info", "warn", "error")

#: rule id -> (default severity, one-line description)
RULES: dict[str, tuple[Severity, str]] = {
    "DTYPE-001": ("error", "more than one float downcast in a matmul program "
                           "(stray round-trip breaks single-downcast "
                           "accumulation discipline)"),
    "DTYPE-002": ("error", "downcast/upcast round-trip: a value is narrowed "
                           "then widened again, losing precision for free"),
    "COLL-001": ("error", "collective inventory mismatch: traced collectives "
                          "differ in kind or count from the analytic comms "
                          "model for the mode"),
    "COLL-002": ("error", "collective byte-volume mismatch vs the analytic "
                          "comms model"),
    "COLL-003": ("error", "collective primitive inside a compute-only "
                          "program (compute legs must be comm-free or the "
                          "compute/comm split is meaningless)"),
    "PURE-001": ("error", "host callback / debug print inside a timed "
                          "program (host round-trips corrupt timing)"),
    "DONATE-001": ("error", "buffer declared reusable does not lower with a "
                            "donation alias (tf.aliasing_output / "
                            "jax.buffer_donor absent)"),
    "PALLAS-001": ("error", "Pallas block shape does not divide the padded "
                            "problem dims it is tuned for"),
    "PALLAS-002": ("error", "Pallas tile misaligned: block dims must align "
                            "to the (8, 128) fp32 tile / 128-wide MXU"),
    "PALLAS-003": ("error", "Pallas VMEM footprint estimate exceeds the "
                            "compiler budget cap"),
    "SPEC-001": ("error", "spec failed to parse/validate"),
    "SPEC-002": ("error", "unknown key in a spec table (silently ignored at "
                          "run time — almost always a typo)"),
    "SPEC-003": ("warn", "sharded size not divisible by the device count "
                         "it will run under"),
    "SPEC-004": ("error", "job fingerprint collision: two distinct jobs "
                          "would share a resume/ledger identity"),
    "SPEC-005": ("error", "invalid tenant definition: weight/priority/SLO "
                          "bounds violated, bad traffic profile, or "
                          "unparseable mix in a [tenants.*] block"),
    "SPEC-006": ("error", "duplicate tenant id: two [tenants.*] blocks "
                          "collide after case/whitespace normalization "
                          "(one tenant's traffic would be billed to the "
                          "other's share)"),
    "REG-001": ("warn", "impl-registry tier routes to a kernel citing no "
                        "measurement artifact"),
    "REG-002": ("info", "impl-registry tier extrapolated by tie policy with "
                        "no tuning-DB cell behind it (promote a cell citing "
                        "a measured artifact or an explicit analytic prior "
                        "— tune promote / scripts/regen_tune_db.py)"),
    "SCHED-001": ("error", "forced serialization: a collective transitively "
                           "consumes the same step's matmul product "
                           "(required on no_overlap baselines, fatal on "
                           "overlap paths — no scheduler may hide it)"),
    "SCHED-002": ("error", "matmul/collective mutual independence broken in "
                           "an overlap body — the precondition for XLA's "
                           "latency-hiding scheduler is gone"),
    "SCHED-003": ("error", "ppermute-ring schedule broken: hop count or hop "
                           "independence no longer matches the ring "
                           "contract"),
    "SCHED-004": ("error", "async collective start/done pairing broken in "
                           "the optimized HLO (start without done, or no "
                           "work scheduled between them)"),
    "MEM-001": ("error", "estimated peak live bytes exceed the per-device "
                         "memory budget"),
    "MEM-002": ("warn", "peak-memory estimate inconsistent with the comms "
                        "model's per-shard payloads (estimator or program "
                        "shape self-check failed)"),
    "DRIFT-001": ("error", "program fingerprint drifted from the golden "
                           "baseline — compiled structure changed without a "
                           "baseline regen (scripts/regen_golden.py)"),
    "DRIFT-002": ("warn", "fingerprint baseline incomplete or stale for a "
                          "traced program (regen "
                          "tests/golden/program_fingerprints.json)"),
    "TUNE-001": ("error", "impl_select route resolves to no tuning-DB cell "
                          "and no declared fallback (a table tier citing a "
                          "committed artifact) — the routing decision has "
                          "no evidence"),
    "TUNE-002": ("warn", "impl_select route resolves to a stale tuning-DB "
                         "cell (jax version moved or the routed program's "
                         "digest drifted) — re-measure or re-promote the "
                         "cell"),
    "COLL-Q-001": ("error", "quantized payload travels without its scale "
                            "side-channel: a wire-dtype collective is not "
                            "paired with a matching fp32 scale collective "
                            "(dequantization downstream is impossible or "
                            "wrong)"),
    "COLL-Q-002": ("error", "quantized collective inventory mismatch: the "
                            "traced wire-format program's collectives "
                            "differ in kind, count, or payload bytes from "
                            "the analytic wire model "
                            "(comms_model.wire_collectives)"),
    "COLL-Q-003": ("error", "predicted payload-byte reduction below the "
                            "2x floor for a 1-byte wire format vs the "
                            "bf16 baseline (the wire format fails its "
                            "reason to exist)"),
    "DTYPE-Q-001": ("error", "quantized program breaks the one-downcast "
                             "contract: non-wire float downcasts exceed "
                             "the exact program's count by more than the "
                             "format's budget, or a new fp32 round-trip "
                             "appeared (dequant must stay in the fp32 "
                             "accumulator until the single final "
                             "downcast)"),
    "DTYPE-Q-002": ("error", "inert short-circuit broken: a world-1 or "
                             "integer-operand program under --comm-quant "
                             "is not identical to the exact program "
                             "(quantization must vanish, not degrade)"),
    "SPEC-007": ("error", "invalid --comm-quant value in a spec's job "
                          "flags: not in the wire-format grammar, or a "
                          "block size that does not divide the payload "
                          "width implied by --sizes/--num-devices"),
    "OBS-001": ("error", "XLA cost_analysis attribution disagrees with the "
                         "hand FLOPs model (utils.metrics.calculate_tflops) "
                         "beyond tolerance — reported TFLOP/s are computed "
                         "from the wrong op count"),
    "OBS-002": ("error", "instrumented entrypoint emitted no metrics "
                         "snapshot, or its snapshot counters do not "
                         "reconcile with the ledger's extras — the obs bus "
                         "and the ledger disagree about what happened"),
    "FAULT-001": ("error", "subprocess spawn site not routed through "
                           "faults/supervisor.supervised_run (and not on "
                           "its allowlist) — the child escapes the "
                           "heartbeat watchdog and signal-escalation "
                           "ladder"),
    "FAULT-002": ("error", "durable JSONL writer (fsync site) not "
                           "registered in faults/audit.WRITER_REGISTRY — "
                           "crash-consistency certification does not know "
                           "this artifact exists"),
    "ART-001": ("error", "artifact store integrity violation: a shipped "
                         "exec_artifact's key does not recompute from its "
                         "own fields, its blob is missing, or the blob "
                         "does not hash to its recorded digest — the "
                         "store would deserialize something other than "
                         "what was certified"),
    "ART-002": ("warn", "stale serialized executable: the artifact's jax "
                        "version or recomputed program digest drifted "
                        "from the store's record — the key mismatch "
                        "makes it dead weight (serving will recompile "
                        "past it); re-export or prune"),
    "TUNE-003": ("error", "measured-online tuning cell cites no serve "
                          "ledger (.jsonl) — an online promotion must "
                          "reference the shadow-traffic stream that "
                          "measured it"),
    "HIST-001": ("error", "metric-history regression: the latest ingest "
                          "round's best reading fell beyond the noise "
                          "band vs the series' last-known-good "
                          "(obs detect; store measurements/history.jsonl)"),
    "HIST-002": ("warn", "metric-history improvement beyond noise not "
                         "reflected in the recorded last-known-good — "
                         "update the gate baseline / tune DB or the "
                         "evidence rots"),
    "HIST-003": ("warn", "recurring history series gone stale: no "
                         "successful ingest for N rounds — the repo "
                         "stopped measuring a cell it used to measure"),
    "HIST-004": ("error", "analytic-vs-measured attribution residual "
                          "moved beyond noise for a (mode × wire-format "
                          "× shape) cell — the compute+comm model "
                          "stopped explaining the machine"),
    "COLL-H-001": ("error", "per-axis collective inventory mismatch on a "
                            "factorized mesh: a traced program's "
                            "(kind, axis) multiset differs from the "
                            "two-level comms model — a collective moved "
                            "to the wrong link class"),
    "COLL-H-002": ("error", "per-axis collective payload mismatch on a "
                            "factorized mesh: right (kind, axis), wrong "
                            "bytes vs the two-level comms model's "
                            "prediction"),
    "COLL-H-003": ("error", "per-link wire-format routing broken: a "
                            "quantized wire dtype appears on an axis whose "
                            "link class the --comm-quant spec left exact, "
                            "or the quantized link's collectives carry no "
                            "wire dtype at all"),
    "MEM-003": ("error", "K-streaming resident window exceeds the "
                         "per-device budget: the analytic window bytes "
                         "(accumulator + staged panel pairs) do not fit "
                         "--mem-budget-gib — the out-of-core mode's one "
                         "job is to bound this"),
    "SPEC-008": ("error", "invalid hierarchical-mesh flag in a spec's job "
                          "flags: --mesh not in the dcn:R,ici:C grammar or "
                          "not covering --num-devices, a malformed "
                          "per-link --comm-quant, or a non-positive "
                          "--stream-k / --mem-budget-gib"),
    "TRACE-001": ("error", "scheduler shed/breaker raise site with no "
                           "adjacent flight-recorder terminal emission — "
                           "a refused request would vanish from the "
                           "per-request trace record"),
    "TRACE-002": ("error", "terminal-span coverage broken: an emission "
                           "site uses an unknown terminal state, a state "
                           "is emitted at more than one site in a file "
                           "(a request could get two terminal spans), or "
                           "a terminal state has no emission site at all"),
    "TRACE-003": ("error", "unbounded exemplar retention: an exemplar "
                           "reservoir is declared without an "
                           "EXEMPLAR_LIMIT bound, or the limit is outside "
                           "its sane range — trace-id retention behind "
                           "tail quantiles must stay small"),
    "TRAIN-001": ("error", "train-step collective inventory mismatch: a "
                           "traced full-step program's (kind, axis) "
                           "multiset differs from the closed-form "
                           "gradient-collective model "
                           "(comms_model.train_expected_collectives) — a "
                           "collective appeared in fwd/bwd, vanished from "
                           "the sync, or moved to the wrong axis"),
    "TRAIN-002": ("error", "train-step collective payload mismatch: right "
                           "(kind, axis), wrong bytes vs the gradient-"
                           "collective model — the wire format rewrote "
                           "the wrong collective (the ZeRO parameter "
                           "allgather must travel exact) or sized a "
                           "chunk wrong"),
    "TRAIN-003": ("error", "ZeRO shard-ownership violation: the per-"
                           "replica updated weight-row shards do not "
                           "tile the parameter disjointly (reduce_scatter "
                           "chunk, owned update slice, and allgather "
                           "reassembly disagree about who owns which "
                           "rows)"),
    "TRAIN-004": ("error", "train-step downcast budget exceeded: the "
                           "quantized-wire step performs more non-wire "
                           "float downcasts than the exact step — "
                           "dequantized gradients must ride the fp32 "
                           "accumulator into the update's single final "
                           "downcast"),
    "TRAIN-005": ("error", "impure train step: a host callback / side-"
                           "effecting primitive inside the timed "
                           "optimizer step — the step must be a pure "
                           "function of (x, w) or the timing split and "
                           "drift series measure the host"),
    "SPEC-009": ("error", "invalid train flag in a spec's job flags: "
                          "--grad-quant not in the wire-format grammar "
                          "(or the legacy control tier, which has no "
                          "reduce_scatter half), a per-link value with "
                          "no factorized --mesh, --zero outside {0,1}, "
                          "--steps < 2 when a drift series is measured, "
                          "or a (mode, mesh) pair the collective model "
                          "rejects"),
    "POD-001": ("error", "replica-group partition does not cover the mesh "
                         "disjointly: a device belongs to zero or to more "
                         "than one group, or a group claims a device "
                         "outside the world — pod placement would route "
                         "traffic onto devices nobody (or everybody) "
                         "owns"),
    "POD-002": ("error", "per-group collective inventory mismatch: a "
                         "traced group executable's (kind, axis, payload) "
                         "multiset differs from the pod comms model "
                         "(comms_model.pod_expected_collectives) at a "
                         "tested factorization — the sharded serving "
                         "program gathers the wrong way or sizes a shard "
                         "wrong"),
    "POD-003": ("error", "cross-group collective: a dispatched group "
                         "program carries a collective over an axis "
                         "outside its own group mesh — one replica "
                         "group's request would synchronize with another "
                         "group's devices, destroying replica isolation"),
    "SPEC-010": ("error", "invalid pod flag in a serve spec's job flags: "
                          "--replica-groups not a positive integer "
                          "dividing the outer axis of --mesh, pod flags "
                          "with no factorized --mesh, --mesh not covering "
                          "--num-devices, or a per-link --comm-quant the "
                          "pod collective model rejects"),
    "CONC-001": ("error", "shared mutable attribute or module global "
                          "written from two or more thread roots with no "
                          "common guarding lock — a lost-update / torn-"
                          "read race under any interleaving the GIL "
                          "happens not to serialize"),
    "CONC-002": ("error", "lock-order cycle: two code paths acquire the "
                          "same locks in opposite orders — two threads "
                          "interleaving those paths deadlock"),
    "CONC-003": ("error", "appender surface touched from a thread role "
                          "other than its declared sole toucher "
                          "(analysis/concurrency.THREAD_ROLES), or an "
                          "appender-shaped method shipped with no "
                          "declaration at all — the one-writer-per-"
                          "ledger contract behind FlightRecorder and the "
                          "FAULT-002 writer registry, statically checked"),
    "CONC-004": ("error", "blocking call (fsync, subprocess, time.sleep, "
                          "AOT compile/serialize) while holding a lock — "
                          "every thread contending that lock stalls "
                          "behind the syscall on the serve hot path"),
    "CONC-005": ("error", "wall-clock or unseeded-randomness call "
                          "reachable from a fault-plan replay root — the "
                          "chaos certifier's converged-state verdict "
                          "assumes replay is a pure function of "
                          "(plan, seed)"),
    "SCHEMA-001": ("error", "record key read by a declared consumer that "
                            "no declared producer writes (and not on the "
                            "family's historical allowlist) — a KeyError "
                            "or silent None waiting for the next ledger"),
    "SCHEMA-002": ("error", "a family's validator does not mention every "
                            "key its schema-scoped producers statically "
                            "write — the validator lags the producer, so "
                            "torn or drifted records pass the gate"),
    "SCHEMA-003": ("warn", "record key written but read by no declared "
                           "consumer anywhere and not on the family's "
                           "OUTPUT_ONLY allowlist with a reviewed reason "
                           "— dead weight in every ledger line"),
    "SCHEMA-004": ("error", "one record key written with structurally "
                            "incompatible value shapes (scalar vs dict "
                            "vs list) across producers of one family — "
                            "consumers cannot branch on luck"),
    "SCHEMA-005": ("error", "record family with a durable writer but no "
                            "declared obs/history.py ingest route and no "
                            "NON_HISTORY reason — the observatory's "
                            "coverage contract made mechanical"),
}


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint finding: a stable rule ID, where it fired, and evidence."""

    rule: str
    where: str
    message: str
    severity: Severity = ""  # defaults to the rule's severity
    details: dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self) -> None:
        if self.rule not in RULES:
            raise ValueError(f"unknown rule id {self.rule!r}")
        sev = self.severity or RULES[self.rule][0]
        if sev not in SEVERITIES:
            raise ValueError(f"unknown severity {sev!r}")
        object.__setattr__(self, "severity", sev)

    def to_record(self) -> dict[str, Any]:
        return {
            "record_type": "lint_finding",
            "rule": self.rule,
            "severity": self.severity,
            "where": self.where,
            "message": self.message,
            "rule_doc": RULES[self.rule][1],
            "details": self.details,
        }


def summarize(findings: list[Finding]) -> dict[str, int]:
    # literal, not a comprehension over SEVERITIES: these keys are the
    # lint_summary contract digest_jsonl renders, and a dict literal
    # keeps them visible to the schema-flow certifier
    counts = {"info": 0, "warn": 0, "error": 0}
    for f in findings:
        counts[f.severity] += 1
    return counts


def worst_severity(findings: list[Finding]) -> Severity | None:
    for sev in ("error", "warn", "info"):
        if any(f.severity == sev for f in findings):
            return sev
    return None


def should_fail(findings: list[Finding], fail_on: Severity) -> bool:
    """Exit-code policy: --fail-on warn trips on warn+error, --fail-on
    error trips on error only."""
    threshold = SEVERITIES.index(fail_on)
    return any(SEVERITIES.index(f.severity) >= threshold for f in findings)


def write_ledger(path: str, findings: list[Finding], *,
                 argv: list[str] | None = None,
                 extra: dict[str, Any] | None = None) -> None:
    """Write the findings ledger: manifest + findings + summary trailer."""
    from tpu_matmul_bench.utils.telemetry import build_manifest

    manifest = build_manifest(argv=argv, extra={"lint": extra or {}})
    with open(path, "w") as fh:
        fh.write(json.dumps(manifest) + "\n")
        for f in findings:
            fh.write(json.dumps(f.to_record()) + "\n")
        fh.write(json.dumps({"record_type": "lint_summary",
                             **summarize(findings)}) + "\n")
