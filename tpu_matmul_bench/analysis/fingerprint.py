"""Program fingerprints + golden-baseline drift gate (DRIFT-*).

Every traced program in the audit surface is canonicalized into a stable
record — opcode multiset, collective inventory (kind + per-shard payload
bytes), operand sharding signature, input shapes/dtypes — and digested to
a short sha256. The canonical form is built from the *jaxpr*, not the
optimized HLO text: jaxpr primitive names, aval shapes, and sharding
specs are deterministic across processes, while HLO text carries unstable
instruction names and metadata that would make every compile a "drift".

The golden baseline (`tests/golden/program_fingerprints.json`, regenerated
by `scripts/regen_golden.py`) pins the digest of every program at both
audit mesh shapes. The drift gate then has three outcomes per program:

- digest matches → silent;
- digest differs → DRIFT-001 (error): the compiled structure changed —
  either an accidental refactor (fix it) or an intentional one (regen the
  baseline in the same PR so the reviewer sees exactly which programs
  moved);
- program missing from the baseline, or baseline naming a program that no
  longer exists → DRIFT-002 (warn): the baseline is incomplete or stale.

The fingerprint inventory covers: every parallelism mode × world, every
overlap scan variant × world, every collective-matmul ring form × world,
every matmul impl × dtype (unsharded avals), and the declared donation
contracts (alias counts — a dead donation changes the digest).
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
from typing import Any

import jax

from tpu_matmul_bench.analysis import jaxpr_tools as jt
from tpu_matmul_bench.analysis.findings import Finding

#: repo-relative golden baseline path
GOLDEN_RELPATH = os.path.join("tests", "golden",
                              "program_fingerprints.json")

FINGERPRINT_WORLDS = (4, 8)

GOLDEN_SCHEMA = 1


def golden_path(root: str | None = None) -> str:
    """Absolute baseline path; `root` defaults to the repo root inferred
    from this package's location."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
    return os.path.join(root, GOLDEN_RELPATH)


# ------------------------------------------------------- canonicalization

def canonical_record(jaxpr: Any, operands: tuple = ()) -> dict[str, Any]:
    """Stable, JSON-serializable structure summary of one traced program."""
    ops: dict[str, int] = {}
    for eqn in jt.iter_eqns(jaxpr):
        name = eqn.primitive.name
        ops[name] = ops.get(name, 0) + 1
    colls = [{"kind": u.kind, "payload_bytes": u.payload_bytes}
             for u in jt.collective_inventory(jaxpr)]
    colls.sort(key=lambda c: (c["kind"], c["payload_bytes"]))
    shardings = []
    for op in operands:
        spec = getattr(getattr(op, "sharding", None), "spec", None)
        shardings.append(str(spec) if spec is not None else "unsharded")
    invars = jaxpr.jaxpr.invars if hasattr(jaxpr, "jaxpr") else jaxpr.invars
    return {
        "ops": dict(sorted(ops.items())),
        "collectives": colls,
        "shardings": shardings,
        "input_shapes": [list(v.aval.shape) for v in invars],
        "input_dtypes": [str(v.aval.dtype) for v in invars],
    }


def digest(record: dict[str, Any]) -> str:
    """Short stable digest of a canonical record."""
    blob = json.dumps(record, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def _record_of(fn: Any, operands: tuple) -> dict[str, Any]:
    return canonical_record(jax.make_jaxpr(fn)(*operands), operands)


# ------------------------------------------------------------- inventory

def program_inventory(worlds=FINGERPRINT_WORLDS) -> dict[str, dict]:
    """Canonical records for every program in the audit surface that the
    active backend can trace. Keys are stable program identities."""
    import jax.numpy as jnp

    from tpu_matmul_bench.analysis import hlo_sched
    from tpu_matmul_bench.analysis.auditor import (
        _IMPL_MATRIX,
        AUDIT_SIZE,
        _all_modes,
        _audit_config,
        _impl_fn,
        donation_contracts,
    )
    from tpu_matmul_bench.parallel.mesh import make_mesh
    from tpu_matmul_bench.parallel.overlap import overlap_mode

    import dataclasses

    records: dict[str, dict] = {}
    avail = len(jax.devices())
    config = _audit_config("bfloat16", "xla")
    # quantized-wire variants are distinct compiled structures (ppermute
    # ring + wire/scale payloads); pinning them separately means a DRIFT
    # golden can never alias a quantized program with its full-precision
    # sibling — one format per wire family (legacy per-row, int8 block,
    # fp8 block)
    quant_formats = ("int8", "int8-block:32", "fp8-block:32")
    quantizable = ("batch_parallel", "data_parallel", "matrix_parallel",
                   "model_parallel")

    for world in worlds:
        if world > avail:
            continue
        mesh = make_mesh(jax.devices()[:world])
        for mode, builder in sorted(_all_modes().items()):
            setup = builder(config, mesh, AUDIT_SIZE)
            fn = setup.full if setup.full is not None else setup.compute
            records[f"mode:{mode}@d{world}"] = _record_of(fn, setup.operands)
            if mode not in quantizable:
                continue
            for fmt in quant_formats:
                qconfig = dataclasses.replace(config, comm_quant=fmt)
                qsetup = builder(qconfig, mesh, AUDIT_SIZE)
                qfn = qsetup.full if qsetup.full is not None \
                    else qsetup.compute
                records[f"mode:{mode}+{fmt}@d{world}"] = _record_of(
                    qfn, qsetup.operands)
        for variant in hlo_sched.SCAN_VARIANTS:
            setup = overlap_mode(config, mesh, hlo_sched.SCHED_SIZE, variant)
            records[f"overlap:{variant}@d{world}"] = _record_of(
                setup.full, setup.operands)
        for kind in ("ag", "ag_bidir", "ag_base", "rs", "rs_bidir",
                     "rs_base"):
            rs = kind.startswith("rs")
            _, x, w = hlo_sched._ring_operands(world, hlo_sched.SCHED_SIZE,
                                               rs)
            fn = _ring_builder(mesh, kind)
            records[f"ring:{kind}@d{world}"] = _record_of(fn, (x, w))

    for impl, dtype_name in list(_IMPL_MATRIX) + [
            ("pallas_ksplit", "bfloat16"), ("pallas_ksplit", "float32")]:
        aval = jax.ShapeDtypeStruct((64, 64), jnp.dtype(dtype_name))
        records[f"impl:{impl}/{dtype_name}"] = _record_of(
            _impl_fn(impl), (aval, aval))

    for name, fn, avals, donate in donation_contracts():
        records[f"donation:{name}"] = {
            "donation_aliases": jt.donation_alias_count(
                fn, avals, donate_argnums=donate),
            "donate_argnums": list(donate),
        }
    return records


def _ring_builder(mesh, kind: str):
    from tpu_matmul_bench.parallel.overlap import (
        collective_matmul_bidir_program,
        collective_matmul_bidir_rs_program,
        collective_matmul_program,
        collective_matmul_rs_program,
    )

    return {
        "ag": lambda: collective_matmul_program(mesh, overlap=True),
        "ag_bidir": lambda: collective_matmul_bidir_program(mesh),
        "ag_base": lambda: collective_matmul_program(mesh, overlap=False),
        "rs": lambda: collective_matmul_rs_program(mesh, overlap=True),
        "rs_bidir": lambda: collective_matmul_bidir_rs_program(mesh),
        "rs_base": lambda: collective_matmul_rs_program(mesh,
                                                        overlap=False),
    }[kind]()


@functools.lru_cache(maxsize=None)
def current_fingerprints(worlds=FINGERPRINT_WORLDS) -> dict[str, str]:
    """Digest map for the whole inventory (cached per process — the audit
    and the tests trace the same ~40 programs; callers must not mutate)."""
    return {key: digest(rec)
            for key, rec in program_inventory(worlds).items()}


# ------------------------------------------------------------ drift gate

def load_golden(path: str | None = None) -> dict[str, str] | None:
    """The baseline's fingerprint map, or None when no baseline exists."""
    path = path or golden_path()
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        doc = json.load(fh)
    return doc.get("fingerprints", {})


def check_drift(current: dict[str, str],
                golden: dict[str, str] | None) -> list[Finding]:
    """Diff current fingerprints against the golden map (pure — seeded
    tests feed perturbed baselines)."""
    if golden is None:
        return [Finding(
            "DRIFT-002", "fingerprint:baseline",
            f"no golden baseline at {GOLDEN_RELPATH} — run "
            "scripts/regen_golden.py and commit the result",
            details={"programs_traced": len(current)})]
    findings: list[Finding] = []
    for key in sorted(current):
        if key not in golden:
            findings.append(Finding(
                "DRIFT-002", f"fingerprint:{key}",
                "program missing from the golden baseline (regen "
                "tests/golden/program_fingerprints.json)",
                details={"digest": current[key]}))
        elif golden[key] != current[key]:
            findings.append(Finding(
                "DRIFT-001", f"fingerprint:{key}",
                f"fingerprint {current[key]} != golden {golden[key]} — "
                "compiled structure changed without a baseline regen "
                "(scripts/regen_golden.py)",
                details={"current": current[key], "golden": golden[key]}))
    for key in sorted(set(golden) - set(current)):
        findings.append(Finding(
            "DRIFT-002", f"fingerprint:{key}",
            "baseline names a program that no longer traces (stale entry "
            "— regen the baseline)",
            details={"golden": golden[key]}))
    return findings


def audit_fingerprints(worlds=FINGERPRINT_WORLDS,
                       path: str | None = None) -> list[Finding]:
    return check_drift(current_fingerprints(worlds), load_golden(path))
