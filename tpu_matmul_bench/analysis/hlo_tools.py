"""Optimized-HLO text parser + def-use reachability + shape accounting.

Promoted from `tests/hlo_deps.py` (which now re-exports from here) so the
lint passes (`analysis/hlo_sched.py`, `analysis/memory_model.py`) and the
scheduling tests share ONE parser. XLA:CPU lowers collectives
synchronously (no `all-reduce-start`/`-done` pairs), so on the CPU mesh
the checkable property is the dependency structure of the optimized HLO:
a collective and a matmul can only be scheduled concurrently (by the TPU
latency-hiding scheduler) if neither reaches the other through def-use
edges. That is exactly the property a refactor would break by serializing
the overlap path, and it is checkable backend-independently.

The parser is deliberately small: instruction names, opcodes, operand
references, called computations, and result types per line — enough for
reachability walks and byte accounting, nothing more.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

_QUOTED = re.compile(r'"[^"]*"')
_COMMENT = re.compile(r"/\*.*?\*/")
_LHS = re.compile(r"^\s*(?:ROOT\s+)?%([\w.-]+)\s*=\s*(.*)$")
_REF = re.compile(r"%([\w.-]+)")
_HEADER = re.compile(r"^(?:ENTRY\s+)?%?([\w.-]+)\s*(?:\(.*)?\{\s*$")
_ENTRY_HEADER = re.compile(r"^ENTRY\s+%?([\w.-]+)", re.MULTILINE)

MATMUL_OPS = ("dot", "dot_general", "convolution")

#: async collective opcode stems the TPU latency-hiding scheduler splits
#: into `<stem>-start` / `<stem>-done` pairs
ASYNC_COLLECTIVE_STEMS = ("all-reduce", "all-gather", "reduce-scatter",
                          "collective-permute", "all-to-all")


@dataclass
class Instruction:
    name: str
    opcode: str
    operands: list[str]          # %refs into the same computation
    called: list[str]            # calls=/to_apply=/body=/condition= comps
    line: str

    def is_opcode(self, *ops: str) -> bool:
        return self.opcode in ops


@dataclass
class Computation:
    name: str
    instructions: dict[str, Instruction] = field(default_factory=dict)


def _opcode_of(rhs: str) -> str:
    """Opcode from an instruction's right-hand side: skip the (possibly
    tuple) result type, take the identifier before the operand parens."""
    rhs = rhs.strip()
    if rhs.startswith("("):  # tuple type — skip the balanced group
        depth = 0
        for i, ch in enumerate(rhs):
            depth += ch == "("
            depth -= ch == ")"
            if depth == 0:
                rhs = rhs[i + 1:].strip()
                break
    m = re.match(r"\S+\s+([\w-]+)\(", rhs)
    return m.group(1) if m else ""


def parse_hlo(text: str) -> dict[str, Computation]:
    """Parse optimized-HLO module text into computations with def-use info.

    Good enough for scheduling assertions: instruction names, opcodes,
    operand references, and called-computation references per line. String
    literals (metadata) are stripped so quoted parens can't confuse the
    opcode/operand scan. Instruction dicts preserve program order (the
    liveness walk in `memory_model` depends on it).
    """
    comps: dict[str, Computation] = {}
    cur: Computation | None = None
    for raw in text.splitlines():
        line = _COMMENT.sub("", _QUOTED.sub('""', raw))
        if cur is None:
            h = _HEADER.match(line.strip())
            # a computation header ends in `{` and is not an instruction
            # (`%name = ...`) — tuple-typed params may contain `(...)`
            if h and not _LHS.match(line):
                cur = Computation(h.group(1))
            continue
        if line.strip() == "}":
            comps[cur.name] = cur
            cur = None
            continue
        m = _LHS.match(line)
        if not m:
            continue
        name, rhs = m.group(1), m.group(2)
        called = re.findall(
            r"(?:calls|to_apply|body|condition)=%([\w.-]+)", rhs)
        # operand refs = %ids inside the first balanced paren group after
        # the opcode; approximated as all %ids minus the called comps
        refs = [r for r in _REF.findall(rhs) if r not in called]
        cur.instructions[name] = Instruction(
            name, _opcode_of(rhs), refs, called, raw.strip())
    return comps


def entry_name(text: str) -> str | None:
    """Name of the module's ENTRY computation, or None if absent."""
    m = _ENTRY_HEADER.search(text)
    return m.group(1) if m else None


def entry_computation(text: str,
                      comps: dict[str, Computation] | None = None
                      ) -> Computation | None:
    comps = comps if comps is not None else parse_hlo(text)
    name = entry_name(text)
    return comps.get(name) if name else None


def find_computations_with(comps: dict[str, Computation],
                           opcode: str) -> list[Computation]:
    return [c for c in comps.values()
            if any(i.opcode == opcode for i in c.instructions.values())]


def instructions_of(comp: Computation, *opcodes: str) -> list[Instruction]:
    return [i for i in comp.instructions.values() if i.opcode in opcodes]


def backward_reach(comp: Computation, start: Instruction) -> set[str]:
    """All instruction names in `comp` reachable backwards (through operand
    edges) from `start`, excluding `start` itself."""
    seen: set[str] = set()
    frontier = list(start.operands)
    while frontier:
        n = frontier.pop()
        if n in seen or n not in comp.instructions:
            continue
        seen.add(n)
        frontier.extend(comp.instructions[n].operands)
    return seen


def _fusion_contains(comps: dict[str, Computation], instr: Instruction,
                     opcodes: tuple[str, ...]) -> bool:
    return any(
        any(i.opcode in opcodes for i in comps[c].instructions.values())
        for c in instr.called if c in comps
    )


def reaches_opcode(comps: dict[str, Computation], comp: Computation,
                   start: Instruction, opcodes: tuple[str, ...]) -> bool:
    """Does `start` transitively depend (backwards) on an instruction with
    one of `opcodes` — either directly or hidden inside a fusion it
    consumes?"""
    for name in backward_reach(comp, start):
        instr = comp.instructions[name]
        if instr.opcode in opcodes:
            return True
        if instr.opcode == "fusion" and _fusion_contains(comps, instr,
                                                         opcodes):
            return True
    return False


def compiled_text(fn, *operands) -> str:
    """Optimized (post-XLA-passes) HLO of a jitted fn on these operands."""
    return fn.lower(*operands).compile().as_text()


_RESULT_SHAPE = re.compile(r"=\s*\(?[a-z]\w*\[([\d,]*)\]")


def result_elems(line: str) -> int:
    """Element count of an instruction's (first) result shape; 0 if the
    line carries no parseable array shape. `f32[]` (scalar) counts as 1."""
    m = _RESULT_SHAPE.search(line)
    if not m:
        return 0
    n = 1
    for d in m.group(1).split(","):
        if d:
            n *= int(d)
    return n


# ------------------------------------------------------------- byte sizes

_TYPE_TOKEN = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")


def _dtype_bytes(token: str) -> float:
    """Bytes per element for an HLO dtype token. The bit width is the
    trailing digit run (`f32`→4, `bf16`→2, `s8`→1); `pred` is 1 byte,
    `f8e4m3fn`-style tokens parse via their leading 8. Sub-byte ints
    (s4/u4) count a conservative full byte."""
    if token == "pred":
        return 1.0
    m = re.match(r"[a-z]+?(\d+)", token)
    if not m:
        return 1.0
    bits = int(m.group(1))
    return max(bits, 8) / 8.0


def type_str_bytes(type_str: str) -> int:
    """Total bytes of every array shape in an HLO type string — a single
    `f32[256,256]{1,0}` or a tuple `(bf16[64,64], s32[])`. Layout braces
    after the shape are ignored; token-only types (`token[]` never parses)
    count 0."""
    total = 0.0
    for dtype_tok, dims in _TYPE_TOKEN.findall(type_str):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _dtype_bytes(dtype_tok)
    return int(total)


def result_type_region(rhs: str) -> str:
    """The result-type region of an instruction's right-hand side: the
    leading balanced paren group for tuple types, else the first token."""
    rhs = rhs.strip()
    if rhs.startswith("("):
        depth = 0
        for i, ch in enumerate(rhs):
            depth += ch == "("
            depth -= ch == ")"
            if depth == 0:
                return rhs[:i + 1]
        return rhs
    parts = rhs.split(None, 1)
    return parts[0] if parts else ""


def result_bytes(instr: Instruction) -> int:
    """Bytes of an instruction's full result (tuples summed) parsed from
    its source line; 0 when the line carries no array type."""
    m = _LHS.match(_QUOTED.sub('""', instr.line))
    if not m:
        return 0
    return type_str_bytes(result_type_region(m.group(2)))
