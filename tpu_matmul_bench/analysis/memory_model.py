"""Static peak-memory estimator over optimized HLO (MEM-*).

Estimates peak live bytes per impl × mode from the compiled (per-shard —
SPMD lowering already splits shapes across the mesh) HLO: walk the entry
computation in program order, give every definition a def→last-use live
interval, and take the max running sum of result bytes. Parameters are
live from their declaration; the ROOT value stays live to the end; a
definition with no user (post-DCE this is rare) is live only at its def
point. Fusion-internal temporaries are invisible to this model — they are
register/scratch-sized by construction, which is exactly why XLA fused
them — so the estimate tracks the buffers that actually occupy HBM.

Two rules:

- MEM-001 (error) — the estimated peak for some mode exceeds the
  per-device budget (``--mem-budget-gib``, default 16 GiB, one v5e HBM).
  At the lint problem size nothing real comes close; the rule exists so a
  refactor that accidentally materializes an unsharded operand (d× the
  bytes) or doubles a carry trips the gate, and so campaigns can set the
  budget to the target device.
- MEM-002 (warn) — self-check against the analytic comms model: every
  collective's per-shard payload must be ≤ the peak estimate (the payload
  buffer is live while the collective runs). A violation means the
  estimator or the program shape is wrong — either way the MEM-001 verdict
  is untrustworthy and says so out loud.
"""

from __future__ import annotations

import functools

import jax

from tpu_matmul_bench.analysis import hlo_tools as ht
from tpu_matmul_bench.analysis.comms_model import expected_collectives
from tpu_matmul_bench.analysis.findings import Finding

#: default per-device budget: one TPU v5e HBM
DEFAULT_BUDGET_GIB = 16.0

#: modes audited — the xla-impl mode matrix; pallas_ring* modes lower
#: through the interpreter on CPU and their HLO buffers are artifacts
MEM_WORLDS = (4, 8)


def estimate_peak_bytes(text: str) -> int:
    """Peak live bytes of the module's entry computation under an analytic
    def→last-use liveness walk in program order."""
    comps = ht.parse_hlo(text)
    entry = ht.entry_computation(text, comps)
    if entry is None:
        return 0
    order = list(entry.instructions.values())  # parse preserves order
    index = {i.name: n for n, i in enumerate(order)}
    last_use = {i.name: n for n, i in enumerate(order)}  # def point itself
    for n, instr in enumerate(order):
        for ref in instr.operands:
            if ref in last_use:
                last_use[ref] = max(last_use[ref], n)
    if order:
        last_use[order[-1].name] = len(order) - 1  # ROOT lives to the end
    # sweep: +bytes at def, -bytes after last use
    delta = [0] * (len(order) + 1)
    for instr in order:
        b = ht.result_bytes(instr)
        if not b:
            continue
        delta[index[instr.name]] += b
        delta[last_use[instr.name] + 1] -= b
    peak = live = 0
    for d in delta:
        live += d
        peak = max(peak, live)
    return peak


def _audit_setup(mode: str, world: int, size: int):
    from tpu_matmul_bench.analysis.auditor import _all_modes, _audit_config
    from tpu_matmul_bench.parallel.mesh import make_mesh

    config = _audit_config("bfloat16", "xla")
    mesh = make_mesh(jax.devices()[:world])
    return config, _all_modes()[mode](config, mesh, size)


@functools.lru_cache(maxsize=None)
def mode_peak_bytes(mode: str, world: int, size: int) -> int:
    """Compile one mode's full program and estimate its per-shard peak
    (cached per process; the CLI reuses this for the ledger manifest)."""
    _, setup = _audit_setup(mode, world, size)
    fn = setup.full if setup.full is not None else setup.compute
    return estimate_peak_bytes(ht.compiled_text(fn, *setup.operands))


def peak_report(worlds=MEM_WORLDS, size: int | None = None
                ) -> dict[str, int]:
    """``{"mode@d{world}": peak_bytes}`` for every auditable mode/world —
    the per-mode peak-memory column the findings-ledger manifest carries."""
    from tpu_matmul_bench.analysis.auditor import AUDIT_SIZE, _all_modes

    size = size or AUDIT_SIZE
    avail = len(jax.devices())
    return {
        f"{mode}@d{world}": mode_peak_bytes(mode, world, size)
        for world in worlds if world <= avail
        for mode in sorted(_all_modes())
    }


def check_budget(peaks: dict[str, int], budget_gib: float,
                 ) -> list[Finding]:
    """MEM-001 over a peak report (pure — seeded tests feed fake peaks)."""
    budget = int(budget_gib * 2**30)
    return [
        Finding(
            "MEM-001", f"mem:{key}",
            f"estimated peak {peak / 2**30:.3f} GiB exceeds the "
            f"{budget_gib:g} GiB per-device budget",
            details={"peak_bytes": peak, "budget_bytes": budget})
        for key, peak in sorted(peaks.items()) if peak > budget
    ]


def check_comms_consistency(mode: str, world: int, size: int,
                            peak: int, dtype) -> list[Finding]:
    """MEM-002: every expected collective payload must fit under the peak
    estimate (the payload buffer is live while the collective runs)."""
    findings = []
    for exp in expected_collectives(mode, world, size, dtype):
        if exp.payload_bytes > peak:
            findings.append(Finding(
                "MEM-002", f"mem:{mode}@d{world}",
                f"peak estimate {peak} B is below the {exp.kind} payload "
                f"{exp.payload_bytes} B the comms model requires live — "
                "the estimator or the program shape is wrong",
                details={"peak_bytes": peak, "kind": exp.kind,
                         "payload_bytes": exp.payload_bytes}))
    return findings


def stream_window_bytes(size: int, dtype, world: int, panels: int,
                        window: int = 2) -> int:
    """Closed-form per-device resident bytes of the K-streaming program
    (ops/stream_k.py): the row-sharded accumulator (fp32/int32 — the
    accumulate-high dtype, NOT the operand dtype) plus BOTH double-buffer
    windows of staged panel pairs (while window w computes, window w+1 is
    already transferring) — A panels row-sharded, B panels replicated.

    Analytic on purpose: the MEM-003 gate must be able to certify a run
    whose FULL operands could never be allocated, so there is no HLO to
    walk — the formula IS the resident-set proof obligation.
    """
    import numpy as np

    from tpu_matmul_bench.ops.stream_k import StreamPlan, acc_dtype

    plan = StreamPlan(size=size, panels=panels, window=window, world=world)
    item = np.dtype(dtype).itemsize
    acc_item = np.dtype(acc_dtype(dtype)).itemsize
    kp = plan.panel_k
    acc_b = (size // world) * size * acc_item
    a_win_b = window * (size // world) * kp * item   # row-sharded panels
    b_win_b = window * kp * size * item              # replicated panels
    return acc_b + 2 * (a_win_b + b_win_b)           # both buffer windows


def check_stream_budget(size: int, dtype, world: int, panels: int,
                        window: int = 2,
                        budget_gib: float = DEFAULT_BUDGET_GIB,
                        ) -> list[Finding]:
    """MEM-003: the streaming window must fit the per-device budget. An
    empty return IS the static certificate the out-of-core runner demands
    before allocating anything."""
    resident = stream_window_bytes(size, dtype, world, panels, window)
    budget = int(budget_gib * 2**30)
    if resident <= budget:
        return []
    return [Finding(
        "MEM-003", f"mem:stream_k@d{world}",
        f"streaming resident window {resident / 2**30:.3f} GiB exceeds the "
        f"{budget_gib:g} GiB per-device budget at {panels} panels × window "
        f"{window} (size {size}) — raise --stream-k or the budget",
        details={"resident_bytes": resident, "budget_bytes": budget,
                 "panels": panels, "window": window})]


def nonstreaming_over_budget(config, world: int, size: int,
                             budget_gib: float) -> dict[str, float]:
    """{mode: estimated per-device GiB} for every non-streaming mode whose
    operand footprint busts the budget at this shape — the contrast half
    of the out-of-core certificate (the same matmul MEM-gates everywhere
    else)."""
    from tpu_matmul_bench.analysis.auditor import _all_modes
    from tpu_matmul_bench.parallel.modes import estimate_memory_gib

    over = {}
    for mode in sorted(_all_modes()):
        gib = estimate_memory_gib(mode, config, world, size,
                                  dp=max(world // 2, 1))
        if gib > budget_gib:
            over[mode] = round(gib, 3)
    return over


def audit_memory(worlds=MEM_WORLDS, size: int | None = None,
                 budget_gib: float = DEFAULT_BUDGET_GIB) -> list[Finding]:
    """Estimate every mode × world peak, gate against the budget, and
    self-check against the comms model."""
    from tpu_matmul_bench.analysis.auditor import (
        AUDIT_SIZE,
        _all_modes,
        _audit_config,
    )

    size = size or AUDIT_SIZE
    config = _audit_config("bfloat16", "xla")
    findings: list[Finding] = []
    avail = len(jax.devices())
    for world in worlds:
        if world > avail:
            findings.append(Finding(
                "MEM-002", f"mesh:d{world}",
                f"cannot audit world={world}: only {avail} devices (run "
                "under XLA_FLAGS=--xla_force_host_platform_device_count)",
                details={"available": avail}))
            continue
        for mode in sorted(_all_modes()):
            peak = mode_peak_bytes(mode, world, size)
            findings.extend(check_budget(
                {f"{mode}@d{world}": peak}, budget_gib))
            findings.extend(check_comms_consistency(
                mode, world, size, peak, config.dtype))
    return findings
