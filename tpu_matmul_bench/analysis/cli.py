"""`python -m tpu_matmul_bench lint` — run the static contract audits.

CPU-only and cheap: programs are traced, never executed, so the whole
audit runs in seconds on a laptop. Exit code 1 when any finding at or
above --fail-on severity fires; the findings ledger (--json-out) is
schema-v2 JSONL like every other program's.

The CLI forces the CPU backend with 8 virtual host devices BEFORE jax
initializes — the mode audits need a multi-device mesh, and lint must
never occupy (or require) a TPU.
"""

from __future__ import annotations

import argparse
import os
import sys
from pathlib import Path

_AUDIT_DEVICE_COUNT = 8


def _force_cpu_backend() -> None:
    """Best-effort CPU + virtual-device setup; must run before the first
    backend query. In-process callers that already initialized a backend
    (tests under conftest's 8-device CPU mesh) pass through untouched."""
    flag = f"--xla_force_host_platform_device_count={_AUDIT_DEVICE_COUNT}"
    xla_flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xla_flags:
        os.environ["XLA_FLAGS"] = f"{xla_flags} {flag}".strip()
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized; trust the caller's setup


def build_parser() -> argparse.ArgumentParser:
    # lazy: auditor imports jax, so main() pins the CPU platform before
    # the parser is built (and plain module import stays jax-free)
    from tpu_matmul_bench.analysis.auditor import audit_groups

    parser = argparse.ArgumentParser(
        prog="lint",
        description="Static contract auditor: jaxpr/HLO checks for every "
                    "impl x mode, plus offline spec validation.",
        epilog="exit codes: 0 = no finding at or above --fail-on severity; "
               "1 = at least one such finding (lint completed — read the "
               "findings); >1 = the linter itself crashed. The HLO pass "
               "family (sched/memory/fingerprint) compiles small programs "
               "on the CPU mesh and adds ~20-30 s; --no-hlo skips it for "
               "quick trace-only runs.")
    parser.add_argument("--fail-on", choices=("warn", "error"),
                        default="error",
                        help="lowest severity that fails the run "
                             "(default: error)")
    parser.add_argument("--json-out", default=None,
                        help="write the findings ledger (schema-v2 JSONL)")
    parser.add_argument("--specs", nargs="*", default=None,
                        help="spec files to lint (default: specs/*.toml "
                             "under the repo root)")
    parser.add_argument("--skip", nargs="*", default=(),
                        choices=audit_groups(),
                        help="audit groups to skip (derived from the "
                             "audit registry — every registered group "
                             "is skippable, nothing else is)")
    parser.add_argument("--no-hlo", action="store_true",
                        help="skip the HLO pass family (sched + memory + "
                             "fingerprint) — the compile-heavy groups")
    parser.add_argument("--mem-budget-gib", type=float, default=None,
                        help="per-device budget for the MEM-001 peak-"
                             "memory gate (default: 16 GiB, one v5e HBM)")
    parser.add_argument("--quiet", action="store_true",
                        help="suppress per-finding lines; print the "
                             "summary only")
    return parser


def _default_specs() -> list[str]:
    spec_dir = Path(__file__).resolve().parents[2] / "specs"
    return sorted(str(p) for p in spec_dir.glob("*.toml"))


def main(argv: list[str] | None = None):
    if argv is None:
        argv = sys.argv[1:]
    # `lint conc selftest` — the concurrency certifier's self-check
    # (real tree clean + seeded rules fire + deterministic findings);
    # jax-free, so it dispatches before any backend setup
    if argv[:1] == ["conc"]:
        if argv[1:] != ["selftest"]:
            print("usage: lint conc selftest", file=sys.stderr)
            raise SystemExit(2)
        from tpu_matmul_bench.analysis.concurrency import run_conc_selftest

        return run_conc_selftest()

    # `lint schema selftest` — the schema-flow certifier's self-check
    # (14 families certify clean + seeded SCHEMA rules fire + repaired
    # twins clean + deterministic findings); jax-free, same contract
    if argv[:1] == ["schema"]:
        if argv[1:] != ["selftest"]:
            print("usage: lint schema selftest", file=sys.stderr)
            raise SystemExit(2)
        from tpu_matmul_bench.analysis.schema_flow import (
            run_schema_selftest)

        return run_schema_selftest()

    _force_cpu_backend()
    args = build_parser().parse_args(argv)

    from tpu_matmul_bench.analysis.auditor import HLO_AUDITS, run_all
    from tpu_matmul_bench.analysis.findings import (
        should_fail,
        summarize,
        write_ledger,
    )

    skip = list(args.skip)
    if args.no_hlo:
        skip.extend(g for g in HLO_AUDITS if g not in skip)

    spec_paths = args.specs if args.specs is not None else _default_specs()
    findings = run_all(spec_paths=spec_paths, skip=skip,
                       mem_budget_gib=args.mem_budget_gib)

    if not args.quiet:
        for f in findings:
            print(f"[{f.severity:5s}] {f.rule} {f.where}: {f.message}")
    counts = summarize(findings)
    print(f"lint: {counts['error']} error(s), {counts['warn']} warning(s), "
          f"{counts['info']} info")

    if args.json_out:
        extra = {"fail_on": args.fail_on,
                 "specs": [str(p) for p in spec_paths],
                 "skipped": skip}
        if "memory" not in skip:
            # per-mode peak-memory column (cached — the audit already
            # compiled these programs)
            from tpu_matmul_bench.analysis.memory_model import peak_report

            extra["peak_memory"] = peak_report()
        write_ledger(args.json_out, findings,
                     argv=list(sys.argv),
                     extra=extra)
        print(f"findings ledger written to {args.json_out}")

    if should_fail(findings, args.fail_on):
        raise SystemExit(1)
    return [f.to_record() for f in findings]


if __name__ == "__main__":
    main()
