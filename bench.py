"""Headline benchmark: single-chip bf16 16k×16k matmul TFLOPS.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} for the
driver. The baseline is the reference's headline number: ~140 TFLOPS for a
single RTX 6000 Ada doing bf16 16384×16384 `torch.matmul`
(reference README.md:43, BASELINE.md). Protocol matches the reference's:
10 warmup + 50 timed iterations (run_scaling_benchmark.sh:16-19).

Runs on the real TPU chip (no platform override). Picks the best of the XLA
and Pallas matmul implementations.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading

BASELINE_TFLOPS = 140.0  # reference README.md:43 — 1× RTX 6000 Ada, bf16 16k

_best = 0.0  # best TFLOPS so far, for the watchdog's last-resort report
_emitted = threading.Lock()  # the one JSON line must print exactly once


def _emit(value: float) -> bool:
    if not _emitted.acquire(blocking=False):
        return False
    # write to the REAL stdout: the human report runs under a process-global
    # redirect_stdout(stderr), and the watchdog thread may fire inside it
    print(
        json.dumps(
            {
                "metric": "bf16_matmul_16k_tflops_per_chip",
                "value": round(value, 2),
                "unit": "TFLOPS",
                "vs_baseline": round(value / BASELINE_TFLOPS, 4),
            }
        ),
        file=sys.__stdout__,
        flush=True,
    )
    return True


def _watchdog(timeout_s: float) -> None:
    """Last-resort exit: the axon TPU tunnel can wedge indefinitely (a killed
    client holds the remote session); if the run exceeds the budget, emit the
    best number seen so far instead of hanging the driver forever."""
    if _emit(_best):  # lost race ⇒ main already emitted; stay silent
        print(f"[bench] watchdog: exceeded {timeout_s:.0f}s, emitted best-so-far",
              file=sys.stderr, flush=True)
        os._exit(0)


def main() -> None:
    global _best
    timeout_s = float(os.environ.get("BENCH_TIMEOUT_S", "3000"))
    timer = threading.Timer(timeout_s, _watchdog, args=(timeout_s,))
    timer.daemon = True
    timer.start()

    from tpu_matmul_bench.utils.config import parse_config
    from tpu_matmul_bench.benchmarks.matmul_benchmark import run

    size = 16384
    best = 0.0
    # three attempts (best-of): the tunneled chip shows ~1% run-to-run
    # variance and the first run eats any session warm-up; each attempt is
    # the full reference protocol (10 warmup + 50 timed iterations). The
    # tuned Pallas kernel is the measured winner (RESULTS_TPU.md), so it
    # gets the warm-up slot and a clean second run; XLA still gets a shot.
    for impl in ("pallas", "xla", "pallas"):
        try:
            config = parse_config(
                [
                    "--sizes", str(size),
                    "--dtype", "bfloat16",
                    "--iterations", "50",
                    "--warmup", "10",
                    "--num-devices", "1",
                    "--matmul-impl", impl,
                ],
                description="bench",
            )
            # keep stdout clean for the single JSON line; human report → stderr
            with contextlib.redirect_stdout(sys.stderr):
                records = run(config)
            if records:
                best = max(best, records[0].tflops_per_device)
                _best = best
        except Exception as e:  # noqa: BLE001 — one impl failing shouldn't zero the bench
            print(f"[bench] impl {impl} failed: {e}", file=sys.stderr)

    timer.cancel()
    _emit(best)


if __name__ == "__main__":
    main()
