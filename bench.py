"""Headline benchmark: single-chip bf16 16k×16k matmul TFLOPS.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"} for the
driver. The baseline is the reference's headline number: ~140 TFLOPS for a
single RTX 6000 Ada doing bf16 16384×16384 `torch.matmul`
(reference README.md:43, BASELINE.md). Protocol matches the reference's:
10 warmup + 50 timed iterations (run_scaling_benchmark.sh:16-19).

Runs on the real TPU chip (no platform override). Picks the best of the XLA
and Pallas matmul implementations.
"""

from __future__ import annotations

import contextlib
import json
import sys

BASELINE_TFLOPS = 140.0  # reference README.md:43 — 1× RTX 6000 Ada, bf16 16k


def main() -> None:
    from tpu_matmul_bench.utils.config import parse_config
    from tpu_matmul_bench.benchmarks.matmul_benchmark import run

    size = 16384
    best = 0.0
    for impl in ("xla", "pallas"):
        try:
            config = parse_config(
                [
                    "--sizes", str(size),
                    "--dtype", "bfloat16",
                    "--iterations", "50",
                    "--warmup", "10",
                    "--num-devices", "1",
                    "--matmul-impl", impl,
                ],
                description="bench",
            )
            # keep stdout clean for the single JSON line; human report → stderr
            with contextlib.redirect_stdout(sys.stderr):
                records = run(config)
            if records:
                best = max(best, records[0].tflops_per_device)
        except Exception as e:  # noqa: BLE001 — one impl failing shouldn't zero the bench
            print(f"[bench] impl {impl} failed: {e}", file=sys.stderr)

    print(
        json.dumps(
            {
                "metric": "bf16_matmul_16k_tflops_per_chip",
                "value": round(best, 2),
                "unit": "TFLOPS",
                "vs_baseline": round(best / BASELINE_TFLOPS, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
