"""Headline benchmark: single-chip bf16 16k×16k matmul TFLOPS.

Prints JSON lines {"metric", "value", "unit", "vs_baseline"} for the
driver, which parses the LAST line. The baseline is the reference's
headline number: ~140 TFLOPS for a single RTX 6000 Ada doing bf16
16384×16384 `torch.matmul` (reference README.md:43, BASELINE.md).
Protocol matches the reference's: 10 warmup + 50 timed iterations
(run_scaling_benchmark.sh:16-19).

Runs on the real TPU chip. The attempt ladder starts with a QUICK rung
(8 fused iterations, tuned Pallas, warm compile cache) whose only job is
to land a real sub-minute nonzero before a flaky tunnel window closes
(rounds 2-4 all delivered 0.0 to the driver because the first attempt
took ~4 minutes), then takes the best of three full-protocol attempts
(tuned Pallas first — the measured winner, RESULTS_TPU.md — then XLA,
then Pallas again; the first run eats session warm-up and the chip shows
~1% run-to-run variance). Attempts use `--timing fused` (all 50 iterations
inside ONE compiled program, serialized by a per-step operand-element
chain — utils/timing.fuse_iterations; records above the chip's physical
ceiling are rejected as protocol artifacts, see MAX_PLAUSIBLE_TFLOPS): the
dispatch-loop protocol measures the host enqueue rate whenever the axon
tunnel's per-RPC latency exceeds the op's ~45 ms device time (observed
2026-07-31: 121 and 50 "TFLOPS" minutes apart on a healthy chip), while
the fused program's single dispatch measures the chip itself — the same
quantity the reference's CUDA events read off a deep stream.

Resilience: the axon tunnel can wedge indefinitely when a relay grant is
stranded (a killed client, or a remote-compile crash mid-RPC — both
observed; killing a waiting client only deepens the wedge). The parent
process therefore never calls into the backend itself: each attempt is
the package's own matmul-benchmark CLI in a child process writing
`--json-out` records, with a soft deadline. A child that blows the soft
deadline is LEFT RUNNING (never killed) and its records are still
collected if it completes within the global budget — so a mid-window
tunnel recovery yields a real measurement instead of a zero.

The emit contract survives ANY termination (round-2 lesson: the driver's
external timeout killed the old end-of-run emit, leaving rc=124 and no
line at all):
  - a provisional 0.0 line prints IMMEDIATELY at startup, so even SIGKILL
    leaves a parseable last line;
  - every time the best-so-far improves, a fresh line prints (the driver
    keeps only the last one, so later improvements overwrite earlier);
  - SIGTERM/SIGINT handlers re-emit the current best before exiting.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

BASELINE_TFLOPS = 140.0  # reference README.md:43 — 1× RTX 6000 Ada, bf16 16k

# v5e bf16 peak is ~197 TFLOPS/chip; no real measurement exceeds it. A
# record above this is a broken protocol (r4: a hoisted fused loop timed
# output copies at 2613 "TFLOPS"), and must never reach the driver.
MAX_PLAUSIBLE_TFLOPS = 220.0

# Attempt ladder: (impl, iterations, warmup) per rung. The FIRST rung is
# deliberately cheap (8 fused iterations, tuned Pallas, warm compile
# cache — sub-minute on a healthy link): its only job is to land a real,
# ceiling-checked nonzero for `_best` before a flaky window closes. The
# full 50-iteration best-of-3 protocol rungs then overwrite it whenever
# the window holds (best-of semantics: a cheap-but-valid number is only
# ever replaced by a better full-protocol one). Round-4 lesson: three
# driver captures in a row read 0.0 because the ladder started with the
# ~4-minute full protocol and the tunnel never stayed up that long.
QUICK_ITERATIONS = 8
QUICK_WARMUP = 2
FULL_ITERATIONS = 50
FULL_WARMUP = 10
ATTEMPTS = (
    # 'auto' = the measured-winner router (ops/impl_select.py) — resolves
    # to the tuned Pallas kernel at bf16 16k; the explicit xla/pallas
    # rungs keep the cross-impl best-of-3 check on the full protocol
    ("auto", QUICK_ITERATIONS, QUICK_WARMUP),   # fast first rung
    ("auto", FULL_ITERATIONS, FULL_WARMUP),
    ("xla", FULL_ITERATIONS, FULL_WARMUP),
    ("pallas", FULL_ITERATIONS, FULL_WARMUP),
)
SOFT_DEADLINE_S = 900.0   # per full attempt; healthy runs finish in ~4 min
QUICK_SOFT_DEADLINE_S = 300.0  # quick rung: healthy runs finish in <1 min
STRAGGLER_GRACE_S = 300.0  # once one result landed, wait this long for more
MAX_SPAWNS = 8            # quick rung + best-of-3 + retries on fast failures
RETRY_BACKOFF_S = 120.0   # between retries when the backend errors fast
POLL_S = 10.0

_best = 0.0  # best TFLOPS seen so far; what every emit reports
# backend-health state carried into every emit so a 0.0 artifact diagnoses
# itself without the reader excavating the stderr tail (r3 lesson: the
# driver's BENCH_r03.json recorded 0.0 with the dead-tunnel traceback
# buried in `tail`): "pending" = no attempt finished yet, "unavailable" =
# an attempt exited nonzero, "slow" = an attempt blew its soft deadline,
# "ok" = a measurement landed
_health = {"backend": "pending", "attempts": 0, "last_rc": None}


_lkg_memo: list = []  # [dict | None] once computed — see _last_known_good


def _last_known_good() -> dict | None:
    """The newest committed fused-headline artifact, so a dead-backend
    0.0 emit can point at the real measured number (and its provenance
    file) instead of leaving the reader with nothing. Read-only file
    scan — the parent still never touches the backend. Computed once and
    memoized: the answer is constant for the process lifetime and _emit
    also runs in the SIGTERM handler, which must stay free of filesystem
    work (main() warms the memo before installing handlers)."""
    if _lkg_memo:
        return _lkg_memo[0]
    import glob
    import re

    repo = os.path.dirname(os.path.abspath(__file__))
    paths = glob.glob(os.path.join(repo, "measurements", "r*",
                                   "headline_fused_pallas.jsonl"))

    def round_no(p: str) -> int:
        m = re.search(r"[/\\]r(\d+)[/\\]", p)
        return int(m.group(1)) if m else -1

    best = None
    # numeric round order — lexicographic would put r10 before r2
    for path in sorted(paths, key=round_no):
        try:
            with open(path) as fh:
                rec = json.loads(fh.read().splitlines()[-1])
            v = float(rec["tflops_per_device"])
        except (OSError, ValueError, KeyError, IndexError, TypeError):
            continue
        if 0.0 < v <= MAX_PLAUSIBLE_TFLOPS:
            best = {"value": round(v, 2),
                    "source": os.path.relpath(path, repo)}
    _lkg_memo.append(best)
    return best


def _emit() -> None:
    rec = {
        "metric": "bf16_matmul_16k_tflops_per_chip",
        "value": round(_best, 2),
        "unit": "TFLOPS",
        "vs_baseline": round(_best / BASELINE_TFLOPS, 4),
        "backend": "ok" if _best > 0.0 else _health["backend"],
        "attempts": _health["attempts"],
    }
    if _best == 0.0 and _health["last_rc"] is not None:
        rec["last_rc"] = _health["last_rc"]
    if _best == 0.0:
        lkg = _last_known_good()
        if lkg is not None:
            rec["last_known_good"] = lkg
    line = json.dumps(rec) + "\n"
    # one os.write of a <PIPE_BUF line is atomic: a SIGTERM-handler emit
    # can never interleave mid-line with a main-thread emit (print() would
    # buffer body and newline separately, risking a garbled last line)
    try:
        try:
            sys.stdout.flush()
        except RuntimeError:
            # signal-handler emit re-entered a buffered flush mid-operation
            # (CPython: 'reentrant call'); os.write below is
            # async-signal-safe and must still land
            pass
        os.write(sys.stdout.fileno(), line.encode())
    except (OSError, ValueError, AttributeError):
        # captured pseudo-stdout without a real fd (test harnesses)
        try:
            print(line, end="", flush=True)
        except RuntimeError:
            pass


def _note_results(outputs: list[str]) -> bool:
    """Re-scan the children's JSONL files; emit if the best improved.
    Returns True iff at least one result has landed so far."""
    global _best
    vals = _collect(outputs)
    if vals and max(vals) > _best:
        _best = max(vals)
        _emit()
    return bool(vals)


def _collect(outputs: list[str]) -> list[float]:
    """TFLOPS from the children's --json-out JSONL files; a half-written
    trailing line (the writer appends records as they finish) parses as
    invalid JSON and is skipped, never mistaken for a result."""
    vals = []
    for path in outputs:
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                rec = json.loads(line)
                v = float(rec["tflops_per_device"])
            except (ValueError, KeyError, TypeError):
                continue
            if v > MAX_PLAUSIBLE_TFLOPS:
                print(f"[bench] rejecting implausible {v:.1f} TFLOPS "
                      f"(> {MAX_PLAUSIBLE_TFLOPS} ceiling) from {path}",
                      file=sys.stderr, flush=True)
                continue
            vals.append(v)
    return vals


def _run_attempts(deadline: float,
                  outputs: list[str] | None = None,
                  procs: list[subprocess.Popen] | None = None) -> None:
    """Spawn/drain measurement attempts until `deadline`. `outputs` and
    `procs` (when given) are shared with the caller so its grace drain can
    keep collecting after the deadline."""
    # BENCH_ARTIFACT_DIR: keep the attempts' raw JSONLs (artifact-hygiene:
    # the driver-captured headline should have files under measurements/);
    # default stays a tmpdir so ad-hoc runs don't litter the repo
    artifact_dir = os.environ.get("BENCH_ARTIFACT_DIR")
    if artifact_dir:
        os.makedirs(artifact_dir, exist_ok=True)
        tmpdir = artifact_dir
    else:
        tmpdir = tempfile.mkdtemp(prefix="bench_")
    outputs = [] if outputs is None else outputs
    procs = [] if procs is None else procs

    # best-of-3 protocol first; past that, keep retrying only while no
    # result has landed (a backend erroring fast — e.g. tunnel UNAVAILABLE
    # after a wedge — may recover mid-budget, and giving up after 3 quick
    # failures would waste the remaining bench window)
    i = 0
    while (time.time() < deadline and i < MAX_SPAWNS
           and (i < len(ATTEMPTS) or not _note_results(outputs))):
        impl, iters, warmup = ATTEMPTS[i % len(ATTEMPTS)]
        quick = iters < FULL_ITERATIONS
        _health["attempts"] = i + 1
        out_path = os.path.join(tmpdir, f"attempt_{i}_{impl}.jsonl")
        outputs.append(out_path)
        print(f"[bench] attempt {i}: {impl} x{iters}"
              + (" (quick rung)" if quick else ""),
              file=sys.stderr, flush=True)
        # test hook: BENCH_CHILD_CMD (JSON argv) replaces the real child so
        # harness tests never touch the backend; "{out}" elements are
        # substituted with the attempt's JSONL path
        child_cmd = os.environ.get("BENCH_CHILD_CMD")
        argv = ([a.replace("{out}", out_path)
                 for a in json.loads(child_cmd)] if child_cmd else
                [sys.executable, "-m",
                 "tpu_matmul_bench.benchmarks.matmul_benchmark",
                 "--sizes", "16384", "--dtype", "bfloat16",
                 "--iterations", str(iters), "--warmup", str(warmup),
                 "--num-devices", "1", "--timing", "fused",
                 "--matmul-impl", impl, "--json-out", out_path])
        # persistent compilation cache: attempt 2+ (and any measure-script
        # run from the same boot) skips the 20-40 s 16k compile — more
        # real measurement attempts fit the budget on a flaky tunnel
        child_env = dict(os.environ)
        child_env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")
        child_env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                             "1")
        procs.append(subprocess.Popen(
            argv,
            # human report → stderr (stdout must stay clean for the JSON
            # lines; the machine channel is the --json-out file)
            stdout=sys.stderr, stderr=sys.stderr, env=child_env,
        ))
        # wait for this attempt, emitting improvements as they land; the
        # quick rung gets a shorter leash so a half-healthy window moves
        # on to (or retries into) other rungs sooner
        soft_s = QUICK_SOFT_DEADLINE_S if quick else SOFT_DEADLINE_S
        attempt_deadline = time.time() + min(
            soft_s, max(0.0, deadline - time.time()))
        timed_out = False
        while True:
            try:
                procs[-1].wait(timeout=min(
                    POLL_S, max(0.0, attempt_deadline - time.time())))
                break
            except subprocess.TimeoutExpired:
                _note_results(outputs)
                if time.time() >= attempt_deadline:
                    timed_out = True
                    break
        has_result = _note_results(outputs)
        if timed_out:
            # soft deadline blown: leave the child running (killing a
            # tunnel client mid-RPC strands the relay grant for everyone —
            # see .claude/skills/verify/SKILL.md) and move on; its late
            # records are still collected in the drain window below
            _health["backend"] = "slow"
            # this attempt has NOT exited — carrying an earlier attempt's
            # rc would misattribute it
            _health["last_rc"] = None
            _emit()  # health change → refresh the parseable last line
            print(f"[bench] attempt {i} ({impl}) slow — continuing "
                  "without killing it", file=sys.stderr, flush=True)
        else:
            if procs[-1].returncode != 0:
                _health["backend"] = "unavailable"
                _health["last_rc"] = procs[-1].returncode
                _emit()
            elif not has_result:
                # clean exit but no parseable record landed (write failed,
                # schema drift): distinct from "pending"/"unavailable" so
                # the 0.0 artifact doesn't contradict its attempt count
                _health["backend"] = "no_result"
                # rc was 0; an earlier failed attempt's rc must not stick
                _health["last_rc"] = None
                _emit()
            # back off only in RETRY mode (past the best-of-3 protocol):
            # protocol attempts use distinct impls, so an impl-specific
            # fast failure shouldn't delay the next impl's attempt
            will_retry = (i + 1 >= len(ATTEMPTS)
                          and i + 1 < MAX_SPAWNS and time.time() < deadline
                          and not has_result)
            if procs[-1].returncode != 0 and will_retry:
                print(f"[bench] attempt {i} ({impl}) failed "
                      f"rc={procs[-1].returncode} — backing off "
                      f"{RETRY_BACKOFF_S:.0f}s before retry",
                      file=sys.stderr, flush=True)
                time.sleep(min(RETRY_BACKOFF_S,
                               max(0.0, deadline - time.time())))
        i += 1

    # drain window: children left running may still land results — wait
    # until all children exited, the straggler grace after the first
    # result expires, or the global budget runs out
    first_result_t: float | None = None
    while time.time() < deadline:
        if _note_results(outputs) and first_result_t is None:
            first_result_t = time.time()
        if all(p.poll() is not None for p in procs):
            break
        if (first_result_t is not None
                and time.time() - first_result_t > STRAGGLER_GRACE_S):
            break
        time.sleep(POLL_S)
    _note_results(outputs)


def main() -> None:
    # Default budget sits well inside any plausible driver timeout (the r2
    # driver killed the old 3000s default at rc=124); with incremental
    # emission the budget now only bounds how long we chase stragglers.
    budget_s = float(os.environ.get("BENCH_TIMEOUT_S", "1500"))
    deadline = time.time() + budget_s - 30  # margin to emit + exit

    def _die(signum, frame):  # noqa: ARG001
        print(f"[bench] signal {signum} — emitting best-so-far and exiting",
              file=sys.stderr, flush=True)
        _emit()
        os._exit(0)

    _last_known_good()  # warm the memo: no filesystem work in handlers
    signal.signal(signal.SIGTERM, _die)
    signal.signal(signal.SIGINT, _die)

    _emit()  # provisional 0.0 line: even SIGKILL leaves a parseable line
    outputs: list[str] = []
    procs: list[subprocess.Popen] = []
    try:
        _run_attempts(deadline, outputs, procs)
    except Exception as e:  # noqa: BLE001 — a JSON line must ALWAYS be last
        print(f"[bench] harness error: {e!r}", file=sys.stderr, flush=True)
    _emit()
    # Grace drain: if nothing landed but children still run (e.g. the
    # tunnel's slow-fail/wedge mode), keep collecting up to a hard cap —
    # with incremental emission the driver's last-line parse picks up a
    # late recovery, and its own timeout bounds us anyway (SIGTERM →
    # handler emits).
    hard_cap = time.time() + max(
        0.0, float(os.environ.get("BENCH_HARD_CAP_S", "2700")) - budget_s)
    while (_best == 0.0 and time.time() < hard_cap
           and any(p.poll() is None for p in procs)):
        time.sleep(30)
        _note_results(outputs)
    _emit()
    # children may still be running (wedged tunnel); don't wait on them
    os._exit(0)


if __name__ == "__main__":
    main()
