#!/usr/bin/env bash
# Multi-process launcher — the torchrun analogue for JAX's multi-controller
# SPMD mode (reference `run_scaling_benchmark.sh:23-31` spawns one process
# per GPU via torch.distributed.run; here each process is one HOST of a
# multi-host cluster and sees all its local devices).
#
# Usage: ./run_multihost_benchmark.sh [NPROCS] [MODE] [DTYPE] [--device=cpu] [extra flags...]
# MULTIHOST_PROGRAM selects the benchmark module (scaling | distributed |
# overlap | collectives | curve | summa | hybrid; default scaling).
#
# Hierarchical meshes: pass --mesh=dcn:R,ici:C (or export MULTIHOST_MESH)
# to factorize the world for summa/hybrid. The process boundary IS the
# DCN hop — each host's local devices sit on ICI — so R should equal the
# process count; the script warns when they disagree but still forwards
# the flag (single-host virtual-mesh rehearsals legitimately mismatch).
#
# Local demo mode (default): spawns NPROCS processes on this machine joined
# through a localhost coordinator. With --device=cpu each process simulates
# a 2-device host (virtual CPU mesh), so world = 2*NPROCS.
# Real pod mode: run this once per host with MULTIHOST_PROC_ID=<host index>
# and MULTIHOST_COORDINATOR=<host0>:<port> exported; the script then execs a
# single process that joins the existing cluster.
set -euo pipefail

NPROCS=${1:-2}
case "${MULTIHOST_PROGRAM:-scaling}" in
  distributed) DEFAULT_MODE=data_parallel ;;
  overlap) DEFAULT_MODE=overlap ;;
  collectives) DEFAULT_MODE=psum ;;
  curve) DEFAULT_MODE=independent ;;
  summa) DEFAULT_MODE=summa ;;
  hybrid) DEFAULT_MODE=hybrid ;;
  *) DEFAULT_MODE=independent ;;
esac
MODE=${2:-$DEFAULT_MODE}
DTYPE=${3:-bfloat16}
EXTRA=()
CPU=0
MESH="${MULTIHOST_MESH:-}"
for arg in "${@:4}"; do
  case "$arg" in
    --device=cpu) CPU=1 ;;
    --device=*) ;;  # device selection is implied by the cluster's backend
    --mesh=*) MESH="${arg#--mesh=}" ;;
    *) EXTRA+=("$arg") ;;
  esac
done
if [[ -n "$MESH" ]]; then
  # the DCN axis crosses the process boundary: its size should match the
  # number of hosts (warn-only — virtual single-host rehearsals differ)
  DCN_SIZE=$(sed -n 's/^dcn:\([0-9]*\).*/\1/p' <<<"$MESH")
  if [[ -n "$DCN_SIZE" && "$DCN_SIZE" != "$NPROCS" ]]; then
    echo "WARNING: --mesh dcn axis is $DCN_SIZE but NPROCS=$NPROCS —" \
         "the DCN hop is the process boundary" >&2
  fi
  EXTRA+=(--mesh "$MESH")
fi

# pick a verified-free port for the local demo (an occupied port would make
# the cluster rendezvous hang until the distributed-init timeout)
free_port() {
  python3 - <<'EOF'
import socket
s = socket.socket()
s.bind(("127.0.0.1", 0))
print(s.getsockname()[1])
s.close()
EOF
}
if [[ -n "${MULTIHOST_PROC_ID:-}" && -z "${MULTIHOST_COORDINATOR:-}" ]]; then
  echo "ERROR: MULTIHOST_PROC_ID is set but MULTIHOST_COORDINATOR is not —" >&2
  echo "every host must rendezvous at the same <host0>:<port> address" >&2
  exit 2
fi
COORD=${MULTIHOST_COORDINATOR:-127.0.0.1:$(free_port)}
export JAX_COORDINATOR_ADDRESS="$COORD"
export JAX_NUM_PROCESSES="$NPROCS"
if [[ $CPU -eq 1 ]]; then
  export JAX_PLATFORMS=cpu
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=2"
  unset PALLAS_AXON_POOL_IPS || true
fi
# Persistent compile cache for every rank: Gloo's transport read timeout
# is shorter than a heavy program's cold compile under load, so compile
# SKEW between ranks can kill the collective one rank is already waiting
# in (observed r5: 'Gloo ReduceScatter failed: Read timeout' on the
# bidir-RS programs). A shared cache keeps ranks' compile times — and a
# retried cluster's — in lockstep.
# (uid-suffixed: a world-shared fixed path owned by another user would
# silently disable the cache and bring the skew race back)
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_cache_multihost_$(id -u)}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-1}"
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

case "${MULTIHOST_PROGRAM:-scaling}" in
  scaling) MODULE=tpu_matmul_bench.benchmarks.matmul_scaling_benchmark ;;
  distributed) MODULE=tpu_matmul_bench.benchmarks.matmul_distributed_benchmark ;;
  overlap) MODULE=tpu_matmul_bench.benchmarks.matmul_overlap_benchmark ;;
  collectives) MODULE=tpu_matmul_bench.benchmarks.collective_benchmark ;;
  curve) MODULE=tpu_matmul_bench.benchmarks.scaling_curve ;;
  summa) MODULE=tpu_matmul_bench.benchmarks.matmul_summa_benchmark ;;
  hybrid) MODULE=tpu_matmul_bench.benchmarks.matmul_hybrid_benchmark ;;
  *) echo "ERROR: unknown MULTIHOST_PROGRAM '${MULTIHOST_PROGRAM}'" >&2; exit 2 ;;
esac
if [[ "${MULTIHOST_PROGRAM:-scaling}" == "summa" || "${MULTIHOST_PROGRAM:-scaling}" == "hybrid" ]]; then
  # summa/hybrid have no --mode (the program IS the mode; grid via
  # --rows / --dp)
  CMD=(python3 -m "$MODULE" --dtype "${DTYPE}" ${EXTRA[@]+"${EXTRA[@]}"})
else
  CMD=(python3 -m "$MODULE"
       --mode "${MODE}" --dtype "${DTYPE}" ${EXTRA[@]+"${EXTRA[@]}"})
fi

if [[ -n "${MULTIHOST_PROC_ID:-}" ]]; then
  export JAX_PROCESS_ID="$MULTIHOST_PROC_ID"
  echo "Joining cluster $COORD as process $JAX_PROCESS_ID/$NPROCS"
  exec "${CMD[@]}"
fi

echo "Running multi-process benchmark: $NPROCS processes, mode=${MODE}, dtype=${DTYPE}, coordinator=$COORD"
WORKER_LOG_DIR=$(mktemp -d)
PIDS=()
# if rank 0 fails, don't orphan workers blocked in collectives; a worker
# stuck in a C++ Gloo read ignores TERM (signal handled only back in
# Python), so follow up with KILL after a short grace — but only when a
# worker actually survived the TERM (no unconditional 2s delay on every
# exit)
reap_workers() {
  kill ${PIDS[@]+"${PIDS[@]}"} 2>/dev/null || true
  local pid alive=0
  for pid in ${PIDS[@]+"${PIDS[@]}"}; do
    kill -0 "$pid" 2>/dev/null && alive=1
  done
  if [[ $alive -eq 1 ]]; then
    sleep 2
    kill -9 ${PIDS[@]+"${PIDS[@]}"} 2>/dev/null || true
  fi
}
trap reap_workers EXIT
for ((i=1; i<NPROCS; i++)); do
  JAX_PROCESS_ID=$i "${CMD[@]}" >"$WORKER_LOG_DIR/worker$i.log" 2>&1 &
  PIDS+=($!)
done
if ! JAX_PROCESS_ID=0 "${CMD[@]}"; then
  echo "rank 0 failed; worker logs in $WORKER_LOG_DIR" >&2
  exit 1
fi
FAILED=0
for pid in ${PIDS[@]+"${PIDS[@]}"}; do
  wait "$pid" || FAILED=1
done
trap - EXIT
if [[ $FAILED -ne 0 ]]; then
  echo "a worker process failed; logs kept in $WORKER_LOG_DIR" >&2
  exit 1
fi
rm -rf "$WORKER_LOG_DIR"
