#!/usr/bin/env bash
# Scaling benchmark launcher ≙ reference `run_scaling_benchmark.sh:3-5`
# (positional NUM_GPUS MODE DTYPE), plus --device=tpu (BASELINE.json).
# Usage: ./run_scaling_benchmark.sh [NUM_DEVICES] [MODE] [DTYPE] [--device=tpu]
#   MODE ∈ {independent, batch_parallel, matrix_parallel}
set -euo pipefail

NUM_DEVICES=${1:-1}
MODE=${2:-independent}
DTYPE=${3:-bfloat16}
DEVICE_FLAG=()
EXTRA=()
for arg in "${@:4}"; do
  case "$arg" in
    --device=*) DEVICE_FLAG=(--device "${arg#--device=}") ;;
    *) EXTRA+=("$arg") ;;  # forwarded verbatim (e.g. --sizes 256 512)
  esac
done

echo "Running scaling benchmark: ${NUM_DEVICES} device(s), mode=${MODE}, dtype=${DTYPE}"
exec python3 -m tpu_matmul_bench.benchmarks.matmul_scaling_benchmark \
  --num-devices "${NUM_DEVICES}" --mode "${MODE}" --dtype "${DTYPE}" ${DEVICE_FLAG[@]+"${DEVICE_FLAG[@]}"} ${EXTRA[@]+"${EXTRA[@]}"}
